"""Zero-cold-start smoke for the persistent AOT compile plane.

The contract under test (ISSUE 17 acceptance): a `myth serve` replica
pointed at a prebaked kernel pack reaches ready WITHOUT compiling the
packed buckets in-process — and keeps doing so after a SIGKILL +
restart, with wave results bit-identical to a packless replica that
paid the compile.

Flow (parent process):

1. child --bake: bake a one-bucket pack for the smoke's dispatch
   shape into a temp dir (the bake wall is the no-pack cold compile);
2. child --serve --pack: spawn a packed replica, measure spawn ->
   ready; assert the pack mounted, readiness cleared, and the
   generic-wave AOT table shows ZERO in-process compiles; settle a
   small contract batch and keep the reports;
3. SIGKILL the packed replica mid-life; restart over the SAME pack;
   assert it is again ready with zero in-process compiles and that
   resubmitting the same contracts yields bit-identical reports;
4. child --serve (no pack): a packless replica pays the in-process
   compile; assert its ready wall exceeds the packed replica's and
   that its reports match the packed ones bit-identically;
5. child --serve --pack with MYTHRIL_NO_AOT=1: the degrade leg — the
   pack is ignored with an ATTRIBUTED reason (`disabled-by-flag`),
   the replica compiles in-process and still serves.

Usage:
    python tools/compileplane_smoke.py          # the full harness
    python tools/compileplane_smoke.py --bake/--serve ... (internal)

Exits 0 on success; prints the failing assertion and exits 1.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: the smoke's dispatch shape — bake and serve MUST agree or the pack
#: cannot cover the service's generic wave bucket
SHAPE = dict(stripes=2, lanes_per_stripe=4, steps_per_wave=64, code_cap=64)

#: tiny full-wave contracts (each < code_cap bytes)
CONTRACTS = [
    "6001600055600060015500",  # storage writer
    "600035600757005b600160005500",  # brancher
    "33ff",  # CALLER; SELFDESTRUCT
]


def _pin_cpu() -> None:
    # this container pins JAX_PLATFORMS through a sitecustomize that
    # ignores env vars; the switch must go through jax.config. The
    # persistent XLA compile cache stays OFF in the serve children:
    # the packless leg must pay the real compile it claims to measure.
    import jax

    jax.config.update("jax_platforms", "cpu")


def child_bake(args) -> int:
    _pin_cpu()
    from mythril_tpu.compileplane.pack import bake_service_pack

    manifest = bake_service_pack(args.pack, [None], **SHAPE)
    print(
        "CP-BAKED "
        + json.dumps({
            "artifacts": manifest["artifacts"],
            "wall_s": manifest["baked"][0]["wall_s"],
        }),
        flush=True,
    )
    return 0


def child_serve(args) -> int:
    _pin_cpu()
    from mythril_tpu.service.engine import ServiceConfig
    from mythril_tpu.service.server import AnalysisServer

    config = ServiceConfig(
        stripes=SHAPE["stripes"],
        lanes_per_stripe=SHAPE["lanes_per_stripe"],
        steps_per_wave=SHAPE["steps_per_wave"],
        code_cap=SHAPE["code_cap"],
        max_waves=3,
        queue_capacity=16,
        host_walk=True,
        execution_timeout=3,
        transaction_count=1,
        coalesce_wait_s=0.05,
        idle_wait_s=0.1,
        arena_warmup=True,
        kernel_pack=args.pack,
    )
    server = AnalysisServer(config).start()
    server.install_signal_handlers()
    print(f"CP-URL {server.url}", flush=True)
    server.engine._warm_done.wait(timeout=600.0)
    print("CP-READY", flush=True)
    try:
        server.drained(timeout_s=None)
    except KeyboardInterrupt:
        pass
    server.close()
    return 0


def spawn_serve(pack: str | None, env_extra: dict | None = None):
    """Returns (proc, url, ready_wall_s): ready_wall is spawn-to-READY
    — interpreter + jax init + mount/compile, the honest cold start."""
    cmd = [sys.executable, os.path.abspath(__file__), "--serve"]
    if pack:
        cmd += ["--pack", pack]
    env = dict(os.environ)
    env.update(env_extra or {})
    t0 = time.monotonic()
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    url = None
    deadline = time.monotonic() + 300.0
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"serve child died at startup (rc {proc.returncode})"
                )
            continue
        if line.startswith("CP-URL "):
            url = line.split(None, 1)[1].strip()
        elif line.startswith("CP-READY"):
            return proc, url, time.monotonic() - t0
    proc.kill()
    raise RuntimeError("serve child never reached ready")


def settle_all(client) -> list:
    """Submit every smoke contract, return its report issue sets (the
    bit-identity payload: title/address/severity per issue)."""
    reports = []
    for i, code in enumerate(CONTRACTS):
        job_id = client.submit(code, idempotency_key=None)
        doc = client.report(job_id, wait_s=240.0)
        assert doc["state"] == "done", f"job {job_id}: {doc['state']}"
        reports.append(sorted(
            (iss.get("title"), iss.get("address"), iss.get("severity"))
            for iss in doc.get("issues") or []
        ))
    return reports


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--bake", action="store_true")
    parser.add_argument("--serve", action="store_true")
    parser.add_argument("--pack", default=None)
    args = parser.parse_args()
    if args.bake:
        return child_bake(args)
    if args.serve:
        return child_serve(args)

    import tempfile

    from mythril_tpu.service.client import ServiceClient

    t_start = time.monotonic()
    root = tempfile.mkdtemp(prefix="myth-cpsmoke-")
    pack_dir = os.path.join(root, "pack")
    summary: dict = {"root": root}

    # -- phase 1: bake ---------------------------------------------------
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--bake",
         "--pack", pack_dir],
        capture_output=True, text=True, timeout=600,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert out.returncode == 0, f"bake failed: {out.stderr[-2000:]}"
    baked = json.loads(
        next(l for l in out.stdout.splitlines()
             if l.startswith("CP-BAKED ")).split(None, 1)[1]
    )
    assert baked["artifacts"] >= 1, f"empty pack: {baked}"
    summary["bake"] = baked

    # -- phase 2: packed replica boots ready, zero compiles --------------
    child, url, ready_pack = spawn_serve(pack_dir)
    client = ServiceClient(url, retries=5, backoff_s=0.2)
    try:
        stats = client.stats()
        plane = stats["kernel"]["compileplane"]
        assert plane["pack_mount"]["mounted"] >= 1, plane
        assert plane["pack_mount"]["refused"] == 0, plane
        assert stats["kernel"]["generic_aot"]["compiles"] == 0, (
            "packed replica compiled its generic wave in-process"
        )
        reports_pack = settle_all(client)
        # the served waves rode the pack too: still zero compiles
        stats = client.stats()
        assert stats["kernel"]["generic_aot"]["compiles"] == 0, (
            "a served wave recompiled a packed bucket"
        )
        assert stats["kernel"]["compileplane"]["kernel_pack_hit_rate"] > 0
        summary["ready_pack_s"] = round(ready_pack, 3)
    finally:
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)

    # -- phase 3: SIGKILL happened above; restart over the same pack -----
    child2, url2, ready_pack2 = spawn_serve(pack_dir)
    client2 = ServiceClient(url2, retries=5, backoff_s=0.2)
    try:
        stats = client2.stats()
        assert stats["kernel"]["compileplane"]["pack_mount"]["mounted"] >= 1
        assert stats["kernel"]["generic_aot"]["compiles"] == 0
        reports_pack2 = settle_all(client2)
        assert reports_pack2 == reports_pack, (
            f"restart changed results: {reports_pack2} != {reports_pack}"
        )
        summary["ready_pack_restart_s"] = round(ready_pack2, 3)
    finally:
        os.kill(child2.pid, signal.SIGKILL)
        child2.wait(timeout=30)

    # -- phase 4: the packless replica pays the compile ------------------
    child3, url3, ready_no_pack = spawn_serve(None)
    client3 = ServiceClient(url3, retries=5, backoff_s=0.2)
    try:
        stats = client3.stats()
        assert stats["kernel"]["compileplane"] == {"enabled": False}, (
            stats["kernel"]["compileplane"]
        )
        reports_no_pack = settle_all(client3)
        assert reports_no_pack == reports_pack, (
            "pack vs no-pack reports diverge: "
            f"{reports_no_pack} != {reports_pack}"
        )
        summary["ready_no_pack_s"] = round(ready_no_pack, 3)
        cold_best = min(ready_pack, ready_pack2)
        assert cold_best < ready_no_pack, (
            f"pack gave no cold-start win: {cold_best} vs {ready_no_pack}"
        )
    finally:
        os.kill(child3.pid, signal.SIGKILL)
        child3.wait(timeout=30)

    # -- phase 5: MYTHRIL_NO_AOT degrade with attribution ----------------
    child4, url4, ready_no_aot = spawn_serve(
        pack_dir, env_extra={"MYTHRIL_NO_AOT": "1"}
    )
    client4 = ServiceClient(url4, retries=5, backoff_s=0.2)
    try:
        stats = client4.stats()
        plane = stats["kernel"]["compileplane"]
        # nothing mounted, and the refusal is attributed, not silent
        assert plane.get("pack_mount", {}).get("mounted", 0) == 0, plane
        reports_no_aot = settle_all(client4)
        assert reports_no_aot == reports_pack
        plane = client4.stats()["kernel"]["compileplane"]
        assert plane.get("unsupported", {}).get("disabled", 0) >= 1, (
            f"degrade reason not attributed: {plane}"
        )
        summary["ready_no_aot_s"] = round(ready_no_aot, 3)
    finally:
        os.kill(child4.pid, signal.SIGKILL)
        child4.wait(timeout=30)

    summary["wall_s"] = round(time.monotonic() - t_start, 1)
    print(f"compileplane smoke OK: {json.dumps(summary)}")
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as why:
        print(f"compileplane smoke FAILED: {why}", file=sys.stderr)
        sys.exit(1)
