"""End-to-end smoke for the resilient analysis supervisor.

Runs a 2-contract corpus under an aggressive wall-clock deadline and
asserts the run produces a well-formed PARTIAL report instead of a
traceback: the contract that fit inside the budget keeps its findings,
the one that didn't is marked skipped with the structured reason, and
the degradation-reason counts the json report surfaces are present.

The corpus is built so the outcome is deterministic, not a timing
race: the first contract is a branch-heavy walk (2^STAGES symbolic
paths) whose execution timeout deliberately outlives the deadline, so
the deadline is guaranteed to be expired by the time the supervisor
reaches the second (cheap) contract's boundary.

Usage:
    python tools/resilience_smoke.py                # 10 s deadline
    python tools/resilience_smoke.py --deadline 5

Exits 0 on success; prints the failing assertion and exits 1 otherwise.
Wall cost is roughly the execution timeout (default 12 s).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

STAGES = 12


def heavy_contract() -> str:
    """2^STAGES symbolic paths: a chain of calldata-dependent JUMPIs,
    each fallthrough writing one storage slot. The host walk cannot
    exhaust this inside the smoke's budget, which is the point."""
    code = bytearray()
    for i in range(STAGES):
        o = len(code)
        dest = o + 11
        # PUSH1 i*32; CALLDATALOAD; PUSH1 dest; JUMPI;
        # PUSH1 1; PUSH1 i; SSTORE; JUMPDEST
        code += bytes([0x60, (i * 32) & 0xFF, 0x35, 0x60, dest, 0x57,
                       0x60, 0x01, 0x60, i, 0x55, 0x5B])
    code.append(0x00)  # STOP
    return code.hex()


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--deadline", type=float, default=10.0,
                        help="run deadline in seconds (default 10)")
    parser.add_argument("--execution-timeout", type=int, default=12,
                        help="per-contract walk timeout; must outlive "
                             "the deadline for a deterministic cut")
    args = parser.parse_args()
    if args.execution_timeout <= args.deadline:
        print("smoke: execution timeout must exceed the deadline "
              "(the first walk has to carry the run past expiry)",
              file=sys.stderr)
        return 2

    from mythril_tpu.analysis.corpus import analyze_corpus
    from mythril_tpu.support import resilience

    marker = resilience.DegradationLog().marker()
    contracts = [
        (heavy_contract(), "", "Heavy"),
        ("33ff", "", "Killable"),  # never reached inside the deadline
    ]
    t0 = time.monotonic()
    results = analyze_corpus(
        contracts,
        transaction_count=2,
        execution_timeout=args.execution_timeout,
        processes=1,
        use_device=False,
        deadline_s=args.deadline,
    )
    wall = time.monotonic() - t0
    reasons = resilience.DegradationLog().counts_since(marker)

    # the partial report, in the shape the json report meta carries
    report = {
        "partial": any(not r["complete"] for r in results),
        "degradation": {
            "reasons": reasons,
            "contracts": [
                {
                    "contract": r["name"],
                    "complete": r["complete"],
                    **({"skipped": r["skipped"]} if r.get("skipped") else {}),
                }
                for r in results
            ],
        },
    }

    try:
        parsed = json.loads(json.dumps(report))  # well-formed: round-trips
        assert len(results) == 2, f"expected 2 results, got {len(results)}"
        heavy, cheap = results
        assert heavy["error"] is None, f"heavy errored: {heavy['error']}"
        assert heavy["complete"], "the in-budget contract must complete"
        assert cheap["skipped"] == "deadline-expired", (
            f"expected the tail skipped at the deadline, got {cheap!r}"
        )
        assert not cheap["complete"] and cheap["error"] is None
        assert parsed["partial"] is True
        assert parsed["degradation"]["reasons"].get("contract-skipped"), (
            f"no contract-skipped reason recorded: {reasons}"
        )
    except AssertionError as why:
        print(f"smoke FAILED after {wall:.1f}s: {why}", file=sys.stderr)
        print(json.dumps(report, indent=2), file=sys.stderr)
        return 1

    print(
        f"smoke OK in {wall:.1f}s: deadline {args.deadline}s cut the run, "
        f"partial report well-formed, reasons={reasons}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
