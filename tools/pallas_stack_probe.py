"""Pallas probe: in-place per-lane stack-slot write vs the one-hot merge.

The step kernel's consolidated stack write (laser/batch/step.py
"consolidated stack/sp write") rewrites the whole [N, S, W] stack
through a one-hot jnp.where every step — the #1 bandwidth term of the
step at big N (SURVEY §7.1 reserves Pallas for exactly this
scatter/compaction class). The Pallas candidate updates ONLY each
lane's written row: a (N,)-grid kernel with scalar-prefetched slot
indices driving the output index_map, stack buffer aliased in-place,
so the bytes touched drop from N*S*W to N*W (128x at S=128).

Run on the real chip:  python tools/pallas_stack_probe.py [N]
Prints per-iteration wall for both implementations over a 64-step
chained scan (forced readback — block_until_ready lies on this link)
plus a correctness check, and is the measured basis for the roadmap's
verdict on the Pallas stack path.

MEASURED VERDICT (2026-08-01, v5e over the tunnel — kept for the
record; see docs/roadmap.md "Pallas stack scatter"): the BlockSpec
route is a dead end on TPU. Mosaic requires the last two block dims
divisible by (8, 128) (doubled sublanes for 16-bit dtypes) unless
equal to the array dims, so a [1, 1, W] per-lane block — the whole
point of the in-place design — cannot be expressed; the smallest
legal block already spans 8 stack slots, and slot indices differ per
lane within any multi-lane block. A hand-rolled HBM DMA kernel
remains possible but unmotivated: the one-hot merge measured here
(44-81 ms/iter at [16384,128,16]) is dominated by the scan carrying a
fresh stack copy per iteration — inside the real jit'd while loop the
carried state is donated/aliased and the merge fuses with adjacent
passes (the ENTIRE 75-fusion step runs at ~26 ms/step), so there is
no 40+ ms standalone write to reclaim.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

N = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
S, W = 128, 16
ITERS = 64


def baseline_write(stack, res_idx, res_val, mask):
    """The step kernel's one-hot merge (full-array rewrite)."""
    slot_ids = jnp.arange(S)[None, :]
    oh = (slot_ids == res_idx[:, None]) & mask[:, None]
    return jnp.where(oh[:, :, None], res_val[:, None, :], stack)


def make_pallas_write():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(idx_ref, mask_ref, val_ref, stack_in_ref, out_ref):
        lane = pl.program_id(0)

        @pl.when(mask_ref[lane] != 0)
        def _():
            out_ref[0, 0, :] = val_ref[0, :]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # res_idx, mask
        grid=(N,),
        in_specs=[
            # the lane's fresh value: one [1, W] row
            pl.BlockSpec((1, W), lambda lane, idx, msk: (lane, 0)),
            # the stack row this lane writes (aliased to the output):
            # scalar-prefetched slot index drives the block placement
            pl.BlockSpec(
                (1, 1, W), lambda lane, idx, msk: (lane, idx[lane], 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, W), lambda lane, idx, msk: (lane, idx[lane], 0)
        ),
    )

    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, S, W), jnp.uint16),
        input_output_aliases={3: 0},  # stack buffer updated in place
    )

    def write(stack, res_idx, res_val, mask):
        return fn(res_idx, mask.astype(jnp.int32), res_val, stack)

    return write


def timed(write_fn, label):
    key = jax.random.PRNGKey(0)
    stack = jnp.zeros((N, S, W), jnp.uint16)
    idx = jax.random.randint(key, (N,), 0, S, dtype=jnp.int32)
    val = jax.random.randint(key, (N, W), 0, 1 << 16).astype(jnp.uint16)
    mask = (jnp.arange(N) % 4) != 0

    @jax.jit
    def loop(stack):
        def body(st, i):
            # chain: rotate the written value so iterations can't fuse
            st = write_fn(st, (idx + i) % S, val + i.astype(jnp.uint16), mask)
            return st, ()

        st, _ = lax.scan(body, stack, jnp.arange(ITERS, dtype=jnp.int32))
        return st

    out = loop(stack)
    _ = np.asarray(out).sum()  # warm + force
    t0 = time.perf_counter()
    out = loop(stack)
    _ = np.asarray(out).sum()
    dt = time.perf_counter() - t0
    print(
        f"{label}: {dt:.3f}s for {ITERS} iters at N={N} "
        f"({dt / ITERS * 1000:.2f} ms/iter)"
    )
    return np.asarray(out)


def main():
    ref = timed(baseline_write, "one-hot merge ")
    try:
        pallas_write = make_pallas_write()
        got = timed(pallas_write, "pallas in-place")
    except Exception as why:
        print(f"pallas path failed: {why!r}")
        return
    if np.array_equal(ref, got):
        print("correctness: pallas output == baseline output")
    else:
        diff = (ref != got).sum()
        print(f"MISMATCH: {diff} differing elements")


if __name__ == "__main__":
    main()
