#!/usr/bin/env python3
"""Static-layer smoke: `myth lint` semantics over the bundled corpus.

Runs the static analysis (analysis/static) over every bundled fixture
plus the synthetic benchmark shapes and FAILS (exit 1) on any
static-summary exception — the CI tripwire for a CFG/dataflow/taint
regression. No device, no jax ops; the whole sweep is milliseconds.

Also enforces the taint-layer budget and the triage tier's liveness:

- the taint pass must stay SUB-SECOND per contract across the sweep
  (a pathological fixpoint would silently tax every service
  admission);
- `static_answer_rate` must be > 0 on the bench corpus (the clean
  shapes exist precisely so the triage tier always has a population —
  a zero rate means the semantic screen regressed into mounting
  everything).

And the cross-contract link leg: the known-positive fixture pairs
(EIP-1967 proxy+impl, EIP-1167 minimal proxy, tainted A-calls-B) must
ALL resolve through the LinkSet — link_resolve_rate 1.0, both proxy
pairs found, sub-second for the whole corpus-level link pass.

Prints one JSON line: per-corpus aggregates (prune rate, dead code,
screen narrowing both ways, answer rate, taint wall) plus any
failures.

Usage: python tools/lint_smoke.py
"""

from __future__ import annotations

import json
import sys
import time
import traceback
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: the per-contract taint budget (seconds) — admission-path work
TAINT_BUDGET_S = 1.0

#: the whole-corpus link-pass budget (seconds) — `myth graph` is a
#: line-rate tool, and the corpus prepass runs this before triage
LINK_BUDGET_S = 1.0


def _link_leg(failures: list) -> dict:
    """The linker smoke: link the known-positive fixture families and
    assert every edge resolves, the pairs pair, and the collision
    fixture collides — within the sub-second budget."""
    from mythril_tpu.analysis.corpusgen import (
        cross_call_pair,
        minimal_proxy,
        proxy_pair,
    )
    from mythril_tpu.analysis.static import link_corpus

    rows = (
        proxy_pair(seed=0, collide=False)
        + proxy_pair(seed=1, collide=True)
        + minimal_proxy(seed=0)
        + cross_call_pair(seed=0)
    )
    try:
        t0 = time.perf_counter()
        linkset = link_corpus(rows)
        stats = linkset.stats()
        wall_s = time.perf_counter() - t0
        assert stats["resolve_rate"] == 1.0, stats
        assert stats["proxy_pairs"] == 3, stats  # 2x eip1967 + eip1167
        assert stats["collisions"] == 1, stats  # the collide=True pair
        assert wall_s < LINK_BUDGET_S, f"link pass took {wall_s:.3f}s"
        checks = {f["check"] for f in linkset.findings()}
        assert "delegatecall-to-upgradeable-target" in checks, checks
        assert "proxy-storage-collision" in checks, checks
        return {
            "link_resolve_rate": stats["resolve_rate"],
            "link_proxy_pairs": stats["proxy_pairs"],
            "link_wall_s": round(wall_s, 3),
        }
    except Exception:
        failures.append(
            {"contract": "<link-leg>", "error": traceback.format_exc(limit=3)}
        )
        return {}


def main() -> int:
    from mythril_tpu.analysis.corpusgen import (
        load_fixtures,
        synth_bench_corpus,
    )
    from mythril_tpu.analysis.static import analyze_bytecode

    bench_rows = [
        (name, code) for code, _creation, name in synth_bench_corpus(32)
    ]
    rows = [(name, code) for name, code in load_fixtures()] + bench_rows
    if not rows:
        print(json.dumps({"error": "no corpus found"}))
        return 1

    failures = []
    pruned = total = dead_instructions = instructions = 0
    modules_skipped = modules_skipped_semantic = 0
    taint_max_ms = 0.0
    bench_answerable = 0
    bench_names = {name for name, _ in bench_rows}
    t0 = time.perf_counter()
    for name, code in rows:
        try:
            summary = analyze_bytecode(code)
            # exercise every surface myth lint renders
            row = summary.lint_dict(name=name)
            assert row["schema_version"] >= 2, row
            applicable, skipped = summary.applicable_modules()
            opcode_applicable, _ = summary.applicable_modules(
                semantic=False
            )
            assert set(applicable) <= set(opcode_applicable), (
                f"{name}: semantic screen mounted a module the opcode "
                "screen rejected"
            )
            pruned += summary.prune_units
            total += summary.total_units
            dead_instructions += summary.dead_instructions
            instructions += summary.n_instructions
            modules_skipped += len(skipped)
            modules_skipped_semantic += len(opcode_applicable) - len(
                applicable
            )
            if summary.taint is not None:
                taint_max_ms = max(taint_max_ms, summary.taint.wall_ms)
                assert summary.taint.wall_ms < TAINT_BUDGET_S * 1e3, (
                    f"{name}: taint pass took {summary.taint.wall_ms}ms "
                    f"(budget {TAINT_BUDGET_S}s)"
                )
            if name in bench_names and summary.static_answerable:
                bench_answerable += 1
        except Exception:
            failures.append(
                {"contract": name, "error": traceback.format_exc(limit=3)}
            )
    static_answer_rate = (
        round(bench_answerable / len(bench_rows), 4) if bench_rows else 0.0
    )
    if not failures and static_answer_rate <= 0.0:
        failures.append(
            {
                "contract": "<bench-corpus>",
                "error": (
                    "static_answer_rate is 0 on the bench corpus — the "
                    "triage tier answers nothing"
                ),
            }
        )
    link_record = _link_leg(failures)
    record = {
        "contracts": len(rows),
        "failures": len(failures),
        **link_record,
        "static_prune_rate": round(pruned / total, 4) if total else 0.0,
        "static_answer_rate": static_answer_rate,
        "dead_instructions": dead_instructions,
        "instructions": instructions,
        "modules_skipped_total": modules_skipped,
        "modules_skipped_semantic": modules_skipped_semantic,
        "taint_max_ms": round(taint_max_ms, 3),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    if failures:
        record["failed"] = failures[:5]
    print(json.dumps(record))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
