#!/usr/bin/env python3
"""Static-layer smoke: `myth lint` semantics over the bundled corpus.

Runs the static analysis (analysis/static) over every bundled fixture
plus the synthetic benchmark shapes and FAILS (exit 1) on any
static-summary exception — the CI tripwire for a CFG/dataflow
regression. No device, no jax ops; the whole sweep is milliseconds.

Prints one JSON line: per-corpus aggregates (prune rate, dead code,
screen narrowing) plus any failures.

Usage: python tools/lint_smoke.py
"""

from __future__ import annotations

import json
import sys
import time
import traceback
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    from mythril_tpu.analysis.corpusgen import (
        load_fixtures,
        synth_bench_corpus,
    )
    from mythril_tpu.analysis.static import analyze_bytecode

    rows = [(name, code) for name, code in load_fixtures()]
    rows += [
        (name, code) for code, _creation, name in synth_bench_corpus(32)
    ]
    if not rows:
        print(json.dumps({"error": "no corpus found"}))
        return 1

    failures = []
    pruned = total = dead_instructions = instructions = 0
    modules_skipped = 0
    t0 = time.perf_counter()
    for name, code in rows:
        try:
            summary = analyze_bytecode(code)
            # exercise every surface myth lint renders
            summary.lint_dict(name=name)
            applicable, skipped = summary.applicable_modules()
            assert applicable, f"{name}: screen emptied the module list"
            pruned += summary.prune_units
            total += summary.total_units
            dead_instructions += summary.dead_instructions
            instructions += summary.n_instructions
            modules_skipped += len(skipped)
        except Exception:
            failures.append(
                {"contract": name, "error": traceback.format_exc(limit=3)}
            )
    record = {
        "contracts": len(rows),
        "failures": len(failures),
        "static_prune_rate": round(pruned / total, 4) if total else 0.0,
        "dead_instructions": dead_instructions,
        "instructions": instructions,
        "modules_skipped_total": modules_skipped,
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    if failures:
        record["failed"] = failures[:5]
    print(json.dumps(record))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
