"""The verdict store's corpus tiers, end to end and host-only:
exact-hit settle, incremental-vs-full issue differential on a fork
corpus, write-back, and --no-store parity. CPU-only, no device — the
walk is the verdict source, which makes the differential exact."""

from __future__ import annotations

import pytest

from mythril_tpu.analysis.corpus import analyze_corpus
from mythril_tpu.analysis.corpusgen import fork_contract
from mythril_tpu.store import close_stores, open_store

pytestmark = pytest.mark.store

BASE = fork_contract(0, 0)
FORK = fork_contract(0, 1)

KW = dict(execution_timeout=8, processes=1, use_device=False)


@pytest.fixture(autouse=True)
def _fresh_store_cache():
    yield
    close_stores()


def _issue_set(result):
    return sorted(
        (i.get("address"), i.get("swc-id")) for i in result["issues"]
    )


@pytest.fixture(scope="module")
def cold_runs():
    """Cold full-analysis baselines, computed once: the base contract
    and the fork, each with NO store in play."""
    base = analyze_corpus([(BASE, "", "base")], store=False, **KW)[0]
    fork = analyze_corpus([(FORK, "", "fork")], store=False, **KW)[0]
    assert base["complete"] and fork["complete"]
    assert base["issues"] and fork["issues"]
    return base, fork


def test_exact_hit_and_incremental_differential(tmp_path, cold_runs):
    cold_base, cold_fork = cold_runs
    store_dir = str(tmp_path / "vstore")
    # cold leg: full analysis + write-back
    first = analyze_corpus(
        [(BASE, "", "base")], store_dir=store_dir, **KW
    )[0]
    assert not first.get("store_hit")
    assert _issue_set(first) == _issue_set(cold_base)
    assert len(open_store(store_dir)) == 1
    # warm leg: the duplicate settles at admission, the one-selector
    # fork re-analyzes incrementally
    warm = analyze_corpus(
        [(BASE, "", "base#dupe"), (FORK, "", "fork")],
        store_dir=store_dir,
        **KW,
    )
    dupe, fork = warm
    assert dupe["store_hit"] is True
    assert dupe["states"] == 0  # no walk, no explorer
    assert _issue_set(dupe) == _issue_set(cold_base)
    assert fork["store_incremental"] is True
    assert fork["store"]["changed_selectors"] == ["0xf0cacc1a"]
    assert fork["store"]["unchanged_selectors"] == ["0xba5eba11"]
    # THE acceptance differential: incremental issue set == a cold
    # full run of the fork
    assert _issue_set(fork) == _issue_set(cold_fork)
    # routing sees the cache economics
    from mythril_tpu.observe.routing import outcome_for

    assert outcome_for(dupe)["route"] == "store-hit"
    assert outcome_for(fork)["route"] == "store-incremental"


def test_no_store_parity(tmp_path, cold_runs):
    """--no-store: identical issue sets, no store flags, nothing
    written — the parity baseline for a suspected stale verdict."""
    cold_base, _ = cold_runs
    store_dir = str(tmp_path / "vstore")
    analyze_corpus([(BASE, "", "base")], store_dir=store_dir, **KW)
    repeat = analyze_corpus(
        [(BASE, "", "base")], store_dir=store_dir, store=False, **KW
    )[0]
    assert not repeat.get("store_hit")
    assert not repeat.get("store_incremental")
    assert _issue_set(repeat) == _issue_set(cold_base)
    # the flag-bag switch is honored too (CLI --no-store path)
    from mythril_tpu.support.support_args import args as support_args

    previous = support_args.store
    support_args.store = False
    try:
        flagged = analyze_corpus(
            [(BASE, "", "base")], store_dir=store_dir, **KW
        )[0]
    finally:
        support_args.store = previous
    assert not flagged.get("store_hit")
    assert _issue_set(flagged) == _issue_set(cold_base)


def test_incremental_bail_falls_back_to_full(tmp_path, cold_runs):
    """A store whose entry lacks fingerprints cannot diff — the fork
    must silently take the full path with the same issues."""
    _, cold_fork = cold_runs
    store_dir = str(tmp_path / "vstore")
    from mythril_tpu.analysis.static import (
        analysis_config_fingerprint,
        summary_for,
    )
    from mythril_tpu.store import code_hash_hex

    store = open_store(store_dir)
    # the fingerprint the corpus run will compute (its defaults)
    config_fp = analysis_config_fingerprint(
        transaction_count=2, create_timeout=10
    )
    # an entry WITH fingerprints (so the near-duplicate probe finds
    # it) but WITHOUT selector spans: plan_incremental must bail and
    # the fork must take the full path
    store.put(
        code_hash_hex(BASE),
        config_fp,
        issues=[{"address": 1, "swc-id": "110"}],
        static={
            "code_len": 57,
            "function_fingerprints": dict(
                summary_for(BASE).function_fingerprints
            ),
        },
    )
    result = analyze_corpus(
        [(FORK, "", "fork")], store_dir=store_dir, **KW
    )[0]
    assert not result.get("store_incremental")
    assert _issue_set(result) == _issue_set(cold_fork)


def test_writeback_skips_incomplete(tmp_path):
    """A deadline-skipped contract must never bank a (partial)
    verdict."""
    store_dir = str(tmp_path / "vstore")
    results = analyze_corpus(
        [(BASE, "", "base")],
        store_dir=store_dir,
        deadline_s=0.000001,  # expired before the first contract
        **KW,
    )
    assert results[0].get("skipped")
    assert len(open_store(store_dir)) == 0
