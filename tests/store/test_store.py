"""Verdict store unit suite: entry lifecycle, key discipline,
corruption refusal, concurrency, eviction, and the incremental diff's
plan/bail logic. Pure host work — no jax, no device."""

from __future__ import annotations

import json
import os
import threading

import pytest

from mythril_tpu.analysis.corpusgen import fork_contract
from mythril_tpu.analysis.static import (
    analysis_config_fingerprint,
    clear_static_cache,
    summary_for,
)
from mythril_tpu.laser.batch.seeds import dispatcher_seeds
from mythril_tpu.store import (
    IncrementalBail,
    SelectorMaskFeed,
    VerdictStore,
    close_stores,
    code_hash_hex,
    merge_banked_issues,
    plan_incremental,
    static_export,
)

pytestmark = pytest.mark.store


@pytest.fixture(autouse=True)
def _fresh_store_cache():
    yield
    close_stores()


def _issue(address: int, swc: str = "110") -> dict:
    return {
        "address": address,
        "swc-id": swc,
        "title": "Test issue",
        "contract": "t",
        "function": "f",
        "description": "d",
        "severity": "Medium",
        "min_gas_used": 0,
        "max_gas_used": 1,
        "sourceMap": None,
        "tx_sequence": None,
    }


def _store(tmp_path, **kw) -> VerdictStore:
    return VerdictStore(str(tmp_path / "vstore"), **kw)


BASE = fork_contract(0, 0)
FORK = fork_contract(0, 1)
FP = "a" * 16


def test_put_get_roundtrip(tmp_path):
    store = _store(tmp_path)
    key = code_hash_hex(BASE)
    summary = summary_for(BASE)
    path = store.put(
        key, FP, issues=[_issue(43)], static=static_export(summary),
        provenance={"computed_by": "test", "wall_s": 1.5},
    )
    assert path and os.path.exists(path)
    entry = store.get(key, FP)
    assert entry is not None
    assert entry.issues == [_issue(43)]
    assert entry.fingerprints == summary.function_fingerprints
    assert entry.provenance["computed_by"] == "test"
    assert entry.code_len == summary.code_len
    assert store.stats()["hits"] == 1
    # a reopened store (fresh process) finds the same entry
    close_stores()
    reopened = VerdictStore(store.dir)
    assert reopened.get(key, FP) is not None


def test_miss_is_counted(tmp_path):
    store = _store(tmp_path)
    assert store.get("00" * 32, FP) is None
    assert store.stats()["misses"] == 1


def test_config_fingerprint_distinguishes_module_sets(tmp_path):
    """The satellite regression: same code, different module set ->
    DISTINCT verdicts, in both the persistent store and the in-memory
    summary LRU."""
    fp_all = analysis_config_fingerprint(modules=None)
    fp_one = analysis_config_fingerprint(modules=["TxOrigin"])
    assert fp_all != fp_one
    store = _store(tmp_path)
    key = code_hash_hex(BASE)
    store.put(key, fp_all, issues=[_issue(43)])
    # the all-modules verdict must NOT answer a restricted-modules run
    assert store.get(key, fp_one) is None
    assert store.get(key, fp_all) is not None
    # the summary LRU keys the same way: no cross-config aliasing
    clear_static_cache()
    s_all = summary_for(BASE, config_fp=fp_all)
    s_one = summary_for(BASE, config_fp=fp_one)
    assert s_all is not s_one
    assert summary_for(BASE, config_fp=fp_all) is s_all


def test_config_fingerprint_covers_tx_count_and_version():
    assert analysis_config_fingerprint(
        transaction_count=1
    ) != analysis_config_fingerprint(transaction_count=2)
    assert analysis_config_fingerprint(
        solver_timeout=1
    ) != analysis_config_fingerprint(solver_timeout=2)


def test_corrupt_entry_refused(tmp_path):
    store = _store(tmp_path)
    key = code_hash_hex(BASE)
    path = store.put(key, FP, issues=[_issue(43)])
    with open(path, "w") as fp:
        fp.write("{not json")
    close_stores()
    fresh = VerdictStore(store.dir)
    base_corrupt = fresh.corrupt  # the open-time scan refuses it too
    assert fresh.get(key, FP) is None
    assert fresh.corrupt > 0 and fresh.corrupt >= base_corrupt
    assert fresh.stats()["misses"] >= 1


def test_tampered_payload_refused(tmp_path):
    store = _store(tmp_path)
    key = code_hash_hex(BASE)
    path = store.put(key, FP, issues=[_issue(43)])
    with open(path) as fp:
        data = json.load(fp)
    data["issues"] = []  # verdict swapped, checksum now stale
    with open(path, "w") as fp:
        json.dump(data, fp)
    assert store.get(key, FP) is None
    assert store.corrupt >= 1


def test_mismatched_key_refused(tmp_path):
    """An entry moved to another key's filename (sync glitch, tamper)
    must never be served under the wrong key."""
    store = _store(tmp_path)
    key_a, key_b = code_hash_hex(BASE), code_hash_hex(FORK)
    path_a = store.put(key_a, FP, issues=[_issue(43)])
    path_b = store.put(key_b, FP, issues=[_issue(56)])
    # overwrite B's file with A's bytes: internally-consistent entry,
    # wrong address
    with open(path_a) as fp:
        blob = fp.read()
    with open(path_b, "w") as fp:
        fp.write(blob)
    assert store.get(key_b, FP) is None
    assert store.corrupt >= 1


def test_concurrent_writers_never_corrupt(tmp_path):
    store = _store(tmp_path)
    errors = []

    def writer(k: int) -> None:
        try:
            for i in range(8):
                # half the threads fight over ONE key, half write
                # distinct keys
                key = code_hash_hex(f"{'00' if k % 2 else '11'}")
                store.put(
                    key, FP, issues=[_issue(i)],
                    provenance={"writer": k, "round": i},
                )
        except Exception as why:  # pragma: no cover
            errors.append(why)

    threads = [
        threading.Thread(target=writer, args=(k,)) for k in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    close_stores()
    fresh = VerdictStore(store.dir)
    assert fresh.corrupt == 0  # every surviving entry verifies
    assert fresh.get(code_hash_hex("00"), FP) is not None
    assert fresh.get(code_hash_hex("11"), FP) is not None


def test_eviction_bounds_entries(tmp_path):
    store = _store(tmp_path, capacity=2)
    for i in range(5):
        store.put(code_hash_hex(f"{i:02x}"), FP, issues=[])
    assert len(store) <= 2
    assert store.stats()["evictions"] >= 3


# -- the incremental diff ------------------------------------------------
def _entry_for(store, code_hex: str, issues) -> object:
    key = code_hash_hex(code_hex)
    store.put(
        key, FP, issues=issues, static=static_export(summary_for(code_hex))
    )
    return store.get(key, FP)


def test_plan_masks_only_unchanged_selector(tmp_path):
    store = _store(tmp_path)
    entry = _entry_for(store, BASE, [_issue(43), _issue(56)])
    plan = plan_incremental(summary_for(FORK), entry)
    assert plan.changed == {"0xf0cacc1a"}
    assert plan.unchanged == {"0xba5eba11"}
    assert plan.mask_selectors == {bytes.fromhex("ba5eba11")}
    # the banked issue is fn B's (56); fn A's (43) is the fresh
    # analysis's job
    assert [i["address"] for i in plan.banked_issues] == [56]
    # and the mask feed actually drops fn B's dispatcher seeds
    feed = plan.mask_feed(summary_for(FORK))
    seeds = dispatcher_seeds(FORK, 68, prune=feed)
    assert feed.seeds_dropped == 2
    assert not any(s.startswith(bytes.fromhex("ba5eba11")) for s in seeds)
    assert any(s.startswith(bytes.fromhex("f0cacc1a")) for s in seeds)


def test_plan_bails_without_fingerprints(tmp_path):
    store = _store(tmp_path)
    key = code_hash_hex(BASE)
    store.put(key, FP, issues=[_issue(43)])  # no static export
    entry = store.get(key, FP)
    with pytest.raises(IncrementalBail) as raised:
        plan_incremental(summary_for(FORK), entry)
    assert raised.value.reason == "fingerprints-absent"


def test_plan_bails_on_cross_selector_state_flow(tmp_path):
    """fn B patched to SLOAD: a changed fn A (SSTORE) can now alter
    what unchanged fn B observes, so the banked fn-B verdict could be
    stale — the plan must refuse."""
    patch = bytes.fromhex("600435")  # CALLDATALOAD(4) in fn B...
    sload = bytes.fromhex("600054")  # ...becomes PUSH1 0; SLOAD
    base = bytes.fromhex(fork_contract(3, 0))
    fork = bytes.fromhex(fork_contract(3, 1))
    fn_b = 44
    assert base[fn_b + 1 : fn_b + 4] == patch
    base = base[: fn_b + 1] + sload + base[fn_b + 4 :]
    fork = fork[: fn_b + 1] + sload + fork[fn_b + 4 :]
    store = _store(tmp_path)
    entry = _entry_for(store, base.hex(), [_issue(43)])
    with pytest.raises(IncrementalBail) as raised:
        plan_incremental(summary_for(fork.hex()), entry)
    assert raised.value.reason == "cross-selector-state-flow"


def test_merge_banked_issues_dedupes():
    issues = [_issue(56)]
    added = merge_banked_issues(issues, [_issue(56), _issue(99)])
    assert added == 1
    assert [i["address"] for i in issues] == [56, 99]


def test_mask_feed_delegates(tmp_path):
    summary = summary_for(BASE)
    feed = SelectorMaskFeed(summary, set(), set())
    assert feed.features == summary.features
    assert feed.code_hash == summary.code_hash
    assert feed.prune_directions() == summary.prune_directions()


# -- fleet-shared directories: concurrent multi-replica writers ----------
# (ISSUE 15: several `myth serve` replicas mount ONE store directory;
# any replica's eviction sweep can unlink any file at any moment, so
# ENOENT mid-scan / mid-evict / mid-get must read as "already gone",
# never as corruption, and never raise.)
def test_second_replica_instance_reads_and_evicts_same_directory(
    tmp_path,
):
    a = _store(tmp_path)
    b = VerdictStore(a.dir)  # a second replica over the SAME files
    a.put(code_hash_hex("aa"), FP, issues=[_issue(1)])
    # b never wrote the entry; the key-derived filename finds it
    assert b.get(code_hash_hex("aa"), FP) is not None
    # b evicts the file out from under a: a's next get is a clean miss
    os.unlink(os.path.join(a.entries_dir, os.listdir(a.entries_dir)[0]))
    before_corrupt = a.corrupt
    assert a.get(code_hash_hex("aa"), FP) is None
    assert a.corrupt == before_corrupt  # vanished, not corrupt


def test_evict_tolerates_entries_vanishing_mid_sweep(
    tmp_path, monkeypatch
):
    store = _store(tmp_path, capacity=2)
    for i in range(4):
        store.put(code_hash_hex(f"{i:02x}"), FP, issues=[])
    # one surviving file vanishes between listdir and the stat (the
    # other replica's sweep won the race)
    victim = sorted(
        n for n in os.listdir(store.entries_dir) if n.endswith(".json")
    )[0]
    real_getmtime = os.path.getmtime

    def racy_getmtime(path):
        if os.path.basename(path) == victim:
            raise FileNotFoundError(path)
        return real_getmtime(path)

    monkeypatch.setattr(os.path, "getmtime", racy_getmtime)
    store.put(code_hash_hex("fe"), FP, issues=[])  # triggers _evict
    assert len(store) <= 3  # the sweep still ran, minus the racer


def test_scan_tolerates_entries_vanishing_mid_open(
    tmp_path, monkeypatch
):
    seed = _store(tmp_path)
    for i in range(3):
        seed.put(code_hash_hex(f"{i:02x}"), FP, issues=[])
    names = sorted(
        n for n in os.listdir(seed.entries_dir) if n.endswith(".json")
    )
    victim = os.path.join(seed.entries_dir, names[0])
    real_open = open

    def racy_open(path, *args, **kwargs):
        if path == victim:
            raise FileNotFoundError(path)
        return real_open(path, *args, **kwargs)

    monkeypatch.setattr("builtins.open", racy_open)
    fresh = VerdictStore(seed.dir)  # open-time _scan hits the race
    assert fresh.corrupt == 0  # vanished entries are not corruption
    monkeypatch.undo()
    assert fresh.get(code_hash_hex("01"), FP) is not None


def test_get_tolerates_entry_vanishing_after_exists_check(
    tmp_path, monkeypatch
):
    store = _store(tmp_path)
    key = code_hash_hex("ab")
    store.put(key, FP, issues=[_issue(2)])
    # exists() says yes, then the file is gone before the read — the
    # narrow window a concurrent evictor can win
    monkeypatch.setattr(os.path, "exists", lambda path: True)
    name = os.listdir(store.entries_dir)[0]
    os.unlink(os.path.join(store.entries_dir, name))
    before = (store.corrupt, store.misses)
    assert store.get(key, FP) is None
    assert store.corrupt == before[0]
    assert store.misses == before[1] + 1
