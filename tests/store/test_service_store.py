"""The verdict-store exact-hit tier at `myth serve` admission.

Engine-less servers throughout (start_engine=False): the hit path
runs on the HTTP thread inside `AnalysisEngine.submit`, so a job that
settles here PROVABLY paid zero queue slots and zero explorer waves —
the wave thread does not exist. CPU-only, sub-second."""

from __future__ import annotations

import pytest

from mythril_tpu.analysis.corpusgen import fork_contract
from mythril_tpu.analysis.static import analysis_config_fingerprint
from mythril_tpu.service.client import ServiceClient, ServiceError
from mythril_tpu.service.engine import ServiceConfig
from mythril_tpu.service.server import AnalysisServer
from mythril_tpu.store import close_stores, code_hash_hex, open_store

pytestmark = [pytest.mark.service, pytest.mark.store]

BANKED = fork_contract(7, 0)
#: CALLER; SELFDESTRUCT — never banked, never statically answerable
UNSEEN = "33ff"

CFG = dict(
    stripes=2,
    lanes_per_stripe=4,
    steps_per_wave=64,
    queue_capacity=4,
    host_walk=False,
)

ISSUES = [
    {
        "address": 43,
        "swc-id": "110",
        "title": "Banked issue",
        "contract": "banked",
        "function": "_function_0xf0cacc21",
        "description": "d",
        "severity": "Medium",
        "min_gas_used": 0,
        "max_gas_used": 1,
        "sourceMap": None,
        "tx_sequence": None,
    }
]


@pytest.fixture()
def store_dir(tmp_path):
    """A store pre-seeded with BANKED's verdict under the fingerprint
    the engine will compute for this ServiceConfig."""
    directory = str(tmp_path / "vstore")
    cfg = ServiceConfig(**CFG)
    fingerprint = analysis_config_fingerprint(
        transaction_count=cfg.transaction_count,
        create_timeout=cfg.create_timeout,
    )
    open_store(directory).put(
        code_hash_hex(BANKED),
        fingerprint,
        issues=ISSUES,
        provenance={"computed_by": "test-seeder", "wall_s": 12.0},
    )
    yield directory
    close_stores()


@pytest.fixture()
def server(store_dir):
    srv = AnalysisServer(
        ServiceConfig(store_dir=store_dir, **CFG), start_engine=False
    ).start()
    yield srv
    srv.close()


def test_repeat_submission_settles_at_admission(server):
    client = ServiceClient(server.url, honor_retry_after=False)
    job_id = client.submit(BANKED)
    job = client.job(job_id)
    # already terminal: no wave thread even exists on this server
    assert job["state"] == "done"
    report = job["report"]
    assert report["store_hit"] is True
    assert report["issues"] == ISSUES
    assert report["store"]["provenance"]["computed_by"] == "test-seeder"
    assert "device" not in report  # no wave block — none ever ran
    stats = client.stats()
    assert stats["store"]["enabled"] is True
    assert stats["store"]["answered"] == 1
    assert stats["store"]["hits"] == 1
    assert stats["waves"]["count"] == 0
    assert stats["queue"]["jobs"].get("done") == 1


def test_unseen_code_queues_normally(server):
    client = ServiceClient(server.url, honor_retry_after=False)
    job_id = client.submit(UNSEEN)
    assert client.job(job_id)["state"] == "queued"
    stats = client.stats()
    assert stats["store"]["answered"] == 0
    assert stats["store"]["misses"] >= 1


def test_hit_skips_full_queue_backpressure(server):
    """Store hits never occupy a queue slot, so repeats keep settling
    even when the pending queue is FULL — exactly the static-answer
    tier's admission contract."""
    client = ServiceClient(server.url, honor_retry_after=False)
    for _ in range(CFG["queue_capacity"]):
        client.submit(UNSEEN)
    with pytest.raises(ServiceError):
        client.submit(UNSEEN)  # 429: the queue is full
    job_id = client.submit(BANKED)
    assert client.job(job_id)["state"] == "done"


def test_no_store_config_disables_tier(store_dir):
    srv = AnalysisServer(
        ServiceConfig(store_dir=store_dir, store=False, **CFG),
        start_engine=False,
    ).start()
    try:
        client = ServiceClient(srv.url, honor_retry_after=False)
        job_id = client.submit(BANKED)
        assert client.job(job_id)["state"] == "queued"
        stats = client.stats()
        assert stats["store"]["enabled"] is False
        assert stats["store"]["answered"] == 0
    finally:
        srv.close()


def test_draining_refuses_store_hits(store_dir):
    srv = AnalysisServer(
        ServiceConfig(store_dir=store_dir, **CFG), start_engine=False
    ).start()
    client = ServiceClient(srv.url, honor_retry_after=False)
    srv.engine.drain(timeout_s=5.0)
    with pytest.raises(ServiceError):
        client.submit(BANKED)  # 503: draining