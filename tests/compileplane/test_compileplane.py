"""Persistent AOT compile plane (ISSUE 17): the content-addressed
artifact cache, the load-before-compile/write-back-after plane facade,
prebaked kernel packs, and the degrade ladder.

The acceptance bar: an artifact survives a cache roundtrip bit-for-bit;
every refusal class (checksum, truncation, filename/key mismatch,
newer schema, backend fingerprint) produces a recompile-shaped MISS and
never a mis-load; a baked pack loads in a FRESH process and produces
bit-identical wave results with zero in-process compiles; MYTHRIL_NO_AOT
degrades every site to the plain jit path with the reason attributed;
concurrent writers never interleave bytes; eviction is LRU-by-access;
and an open TIER_COMPILEPLANE breaker routes every load/store around
the directory. Everything runs on CPU JAX.
"""

import hashlib
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mythril_tpu.compileplane import aot
from mythril_tpu.compileplane.cache import (
    ARTIFACT_SCHEMA_VERSION,
    ArtifactCache,
)
from mythril_tpu.compileplane.fingerprint import (
    backend_fingerprint,
    fingerprint_hex,
)
from mythril_tpu.compileplane.keys import (
    artifact_key,
    bucket_key,
    entry_digest,
    phases_from_bucket,
)
from mythril_tpu.compileplane.pack import (
    bake_service_pack,
    gc_pack,
    list_pack,
    mine_buckets,
    read_manifest,
    verify_pack,
)
from mythril_tpu.compileplane.plane import (
    CompilePlane,
    active_plane,
    configure_plane,
    install_plane,
    reset_plane,
)
from mythril_tpu.laser.batch import specialize as sp
from mythril_tpu.laser.batch.run import (
    clear_aot_generic,
    generic_aot_stats,
    run,
    wave_entry_digest,
    wave_run,
)
from mythril_tpu.laser.batch.state import make_batch, make_code_table
from mythril_tpu.support import breaker as cb
from mythril_tpu.support.resilience import arm_fault, disarm_faults
from mythril_tpu.support.support_args import args as support_args

pytestmark = pytest.mark.compileplane

#: the tiny bake shape every pack test targets (one generic compile
#: per session, amortized by the module-scoped fixture below)
SHAPE = dict(stripes=2, lanes_per_stripe=2, steps_per_wave=32,
             code_cap=32)

WRITER = "6001600055600060015500"


def _pack_arena(shape, codes=None):
    """(batch, table) of the exact avals a SHAPE-configured engine
    dispatches (rows = stripes + 1 — the halt row rides the table).
    Values are free: the kernels are value-independent, so any codes
    of the right row count share one executable."""
    n = shape["n_lanes"]
    batch = make_batch(
        n,
        code_ids=np.full((n,), shape["stripes"], np.int32),
        calldata=[b""] * n,
    )
    rows = shape["stripes"] + 1
    table = make_code_table(
        (codes or [b"\x00"]) * rows, code_cap=shape["code_cap"]
    )
    return batch, table


def _service_shape_dict():
    from mythril_tpu.compileplane.pack import service_shape

    return service_shape(**SHAPE)


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends with no plane, no generic AOT map,
    no armed faults, and a closed compileplane breaker."""
    reset_plane()
    clear_aot_generic()
    disarm_faults()
    cb.reset_all()
    yield
    reset_plane()
    clear_aot_generic()
    disarm_faults()
    cb.reset_all()


def _write_ok(cache, payload=b"payload-bytes", phases=None,
              digest="d" * 24):
    fp = backend_fingerprint()
    fph = fingerprint_hex(fp)
    key = artifact_key(bucket_key(phases), digest, fph)
    path = cache.write(key, bucket_key(phases), digest, fp, fph, payload)
    assert path is not None
    return key, fph, payload


# -- the artifact cache ------------------------------------------------------
def test_artifact_roundtrip(tmp_path):
    cache = ArtifactCache(str(tmp_path))
    key, fph, payload = _write_ok(cache, b"\x00\x01binary\xff" * 100)
    got = cache.read(key, expected_fp=fph)
    assert got is not None
    header, blob = got
    assert blob == b"\x00\x01binary\xff" * 100
    assert header["key"] == key
    assert header["schema_version"] == ARTIFACT_SCHEMA_VERSION
    assert header["fingerprint_hex"] == fph
    assert header["bucket"] == {"kind": "generic"}
    assert header["provenance"]["pid"] == os.getpid()
    assert cache.hits == 1 and cache.corrupt == 0


def test_missing_artifact_is_plain_miss(tmp_path):
    """A vanished file is another replica's eviction, not corruption:
    no corrupt counter, no log noise — the fleet-shared contract."""
    cache = ArtifactCache(str(tmp_path))
    assert cache.read("f" * 40) is None
    assert cache.misses == 1 and cache.corrupt == 0


def test_checksum_refusal_recompiles_never_loads(tmp_path):
    cache = ArtifactCache(str(tmp_path))
    key, fph, _ = _write_ok(cache)
    path = cache._path(key)
    raw = open(path, "rb").read()
    # flip one payload byte past the header line
    cut = raw.index(b"\n") + 2
    with open(path, "wb") as fp:
        fp.write(raw[:cut] + bytes([raw[cut] ^ 0xFF]) + raw[cut + 1:])
    assert cache.read(key, expected_fp=fph) is None
    assert cache.corrupt == 1 and cache.misses == 1


def test_truncated_payload_refused(tmp_path):
    cache = ArtifactCache(str(tmp_path))
    key, fph, _ = _write_ok(cache)
    path = cache._path(key)
    raw = open(path, "rb").read()
    with open(path, "wb") as fp:
        fp.write(raw[:-3])
    assert cache.read(key, expected_fp=fph) is None
    assert cache.corrupt == 1


def test_moved_artifact_key_mismatch_refused(tmp_path):
    """A renamed/copied artifact whose header key disagrees with its
    filename is tampering, not a hit."""
    cache = ArtifactCache(str(tmp_path))
    key, fph, _ = _write_ok(cache)
    other = "0" * 40
    os.rename(cache._path(key), cache._path(other))
    assert cache.read(other, expected_fp=fph) is None
    assert cache.corrupt == 1


def test_newer_schema_refused(tmp_path):
    """A rolled-back replica must refuse a newer writer's artifacts,
    not misparse them."""
    cache = ArtifactCache(str(tmp_path))
    key, fph, payload = _write_ok(cache)
    path = cache._path(key)
    raw = open(path, "rb").read()
    header = json.loads(raw[: raw.index(b"\n")])
    header["schema_version"] = ARTIFACT_SCHEMA_VERSION + 1
    with open(path, "wb") as fp:
        fp.write(json.dumps(header, sort_keys=True).encode())
        fp.write(b"\n")
        fp.write(payload)
    assert cache.read(key, expected_fp=fph) is None
    assert cache.corrupt == 1


def test_fingerprint_mismatch_refused(tmp_path):
    """An artifact from another jax/jaxlib/device is stale, never
    loaded — the toolchain-upgrade safety rail."""
    cache = ArtifactCache(str(tmp_path))
    key, fph, _ = _write_ok(cache)
    assert cache.read(key, expected_fp="not-this-backend") is None
    assert cache.corrupt == 1
    # same artifact under the right fingerprint still loads
    assert cache.read(key, expected_fp=fph) is not None


def test_lru_eviction_by_access(tmp_path):
    cache = ArtifactCache(str(tmp_path), capacity=2)
    keys = []
    for i in range(3):
        digest = f"{i:024d}"
        key, fph, _ = _write_ok(cache, payload=b"x", digest=digest)
        keys.append(key)
        # deterministic mtime order without sleeping
        os.utime(cache._path(key), (1000 + i, 1000 + i))
    cache.evict()
    assert len(cache) == 2
    assert not os.path.exists(cache._path(keys[0]))  # oldest went
    # a READ refreshes mtime: keys[1] touched now outlives keys[2]
    os.utime(cache._path(keys[2]), (2000, 2000))
    assert cache.read(keys[1], expected_fp=fph) is not None
    digest = "9" * 24
    key4, _, _ = _write_ok(cache, payload=b"x", digest=digest)
    assert len(cache) == 2
    assert os.path.exists(cache._path(keys[1]))
    assert not os.path.exists(cache._path(keys[2]))


def test_concurrent_writers_never_interleave(tmp_path):
    """N threads hammering the same directory (same and different
    keys): every surviving artifact verifies — the atomic tmp+rename
    discipline."""
    cache = ArtifactCache(str(tmp_path), capacity=64)
    fp = backend_fingerprint()
    fph = fingerprint_hex(fp)
    payloads = {
        f"{i:024d}": bytes([i]) * (1000 + i) for i in range(8)
    }
    errors = []

    def _hammer(seed):
        try:
            for rep in range(5):
                for digest, payload in payloads.items():
                    key = artifact_key(bucket_key(None), digest, fph)
                    cache.write(key, bucket_key(None), digest, fp, fph,
                                payload)
        except Exception as why:  # pragma: no cover
            errors.append(why)

    threads = [threading.Thread(target=_hammer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for digest, payload in payloads.items():
        key = artifact_key(bucket_key(None), digest, fph)
        got = cache.read(key, expected_fp=fph)
        assert got is not None and got[1] == payload
    assert cache.corrupt == 0


# -- keys --------------------------------------------------------------------
def test_entry_digest_covers_statics_and_avals():
    """max_steps/unroll/donate are BAKED into an AOT executable (unlike
    the in-process warm key) — each must fork the digest; values must
    not."""
    a = jnp.zeros((4, 8), jnp.uint8)
    b = jnp.ones((4, 8), jnp.uint8)
    base = entry_digest("generic", False, {"max_steps": 64}, (a,))
    assert entry_digest("generic", False, {"max_steps": 64}, (b,)) == base
    assert entry_digest("generic", False, {"max_steps": 65}, (a,)) != base
    assert entry_digest("generic", True, {"max_steps": 64}, (a,)) != base
    assert entry_digest("run", False, {"max_steps": 64}, (a,)) != base
    wide = jnp.zeros((4, 16), jnp.uint8)
    assert entry_digest("generic", False, {"max_steps": 64}, (wide,)) != base


def test_bucket_key_roundtrip():
    phases = sp.phases_for(sp.signature_for(bytes.fromhex(WRITER)))
    bucket = bucket_key(phases)
    assert bucket["kind"] == "spec"
    back = phases_from_bucket(bucket)
    assert back == phases
    assert bucket_key(None) == {"kind": "generic"}
    assert phases_from_bucket({"kind": "generic"}) is None
    # an unknown pruned name from a newer writer is ignored, not fatal
    noisy = dict(bucket, pruned=list(bucket["pruned"]) + ["hoverboards"])
    assert phases_from_bucket(noisy) is not None


def test_fingerprint_covers_backend_identity():
    fp = backend_fingerprint()
    for field in ("jax", "jaxlib", "backend", "device_kind", "xla_flags"):
        assert field in fp
    assert fingerprint_hex(dict(fp, jax="999.0.0")) != fingerprint_hex(fp)


# -- the plane facade --------------------------------------------------------
def _tiny_compiled():
    """A real XLA executable that compiles in milliseconds — the plane
    plumbing doesn't care that it isn't a wave kernel."""
    fn = jax.jit(lambda x: x * 2 + 1)
    x = jnp.arange(8, dtype=jnp.int32)
    return fn.lower(x).compile(), x


def test_plane_store_then_fresh_plane_load(tmp_path):
    compiled, x = _tiny_compiled()
    plane = CompilePlane(cache_dir=str(tmp_path))
    digest = entry_digest("generic", False, {"k": 1}, (x,))
    assert plane.store(None, digest, compiled) is not None
    assert plane.stores == 1

    fresh = CompilePlane(cache_dir=str(tmp_path))
    loaded = fresh.load(None, digest)
    assert loaded is not None
    assert fresh.cache_hits == 1 and fresh.misses == 0
    np.testing.assert_array_equal(
        np.asarray(loaded(x)), np.asarray(compiled(x))
    )
    # second load answers from memory, not disk
    assert fresh.load(None, digest) is not None
    assert fresh.mem_hits == 1
    assert fresh.load(None, "0" * 24) is None
    assert fresh.misses == 1


def test_no_aot_env_disables_every_site(tmp_path, monkeypatch):
    """MYTHRIL_NO_AOT: the plane refuses to play, the wave entry is
    exactly the plain jit path, and the reason is attributed."""
    monkeypatch.setenv("MYTHRIL_NO_AOT", "1")
    plane = configure_plane(cache_dir=str(tmp_path))
    assert not plane.usable()
    compiled, x = _tiny_compiled()
    digest = entry_digest("generic", False, {}, (x,))
    assert plane.load(None, digest) is None
    assert plane.store(None, digest, compiled) is None
    assert plane.unsupported.get(aot.REASON_DISABLED, 0) == 2
    assert len(plane.cache) == 0

    # wave_run degrades to the plain path: no AOT entries, no
    # artifacts (same avals as the pack shape, so the jit compile this
    # pays is reused by the baseline differential below)
    shape = _service_shape_dict()
    batch, table = _pack_arena(shape, codes=[bytes.fromhex(WRITER)])
    out, steps = wave_run(batch, table,
                          max_steps=shape["steps_per_wave"],
                          track_coverage=True, donate=False)
    ref_out, ref_steps = run(batch, table,
                             max_steps=shape["steps_per_wave"],
                             track_coverage=True)
    assert int(steps) == int(ref_steps)
    np.testing.assert_array_equal(
        np.asarray(out.status), np.asarray(ref_out.status)
    )
    assert generic_aot_stats() == {"entries": 0, "compiles": 0}
    assert len(plane.cache) == 0


def test_no_aot_flag_parity(tmp_path):
    """The CLI --no-aot switch (support_args.aot) disables the plane
    exactly like the env knob."""
    before = support_args.aot
    support_args.aot = False
    try:
        plane = configure_plane(cache_dir=str(tmp_path))
        assert not plane.usable()
        assert not aot.aot_enabled()
    finally:
        support_args.aot = before
    assert aot.aot_enabled()


def test_serialize_failure_attributed_not_breaker_failure(tmp_path):
    """A capability miss (this object can't serialize) books a reason
    and degrades; it is NOT tier sickness — the breaker stays
    closed."""
    plane = CompilePlane(cache_dir=str(tmp_path))
    assert plane.store(None, "a" * 24, object()) is None
    assert plane.unsupported.get(aot.REASON_SERIALIZE, 0) == 1
    assert plane.store_failures == 0
    assert cb.breaker(cb.TIER_COMPILEPLANE).state == cb.STATE_CLOSED


def test_corrupt_blob_deserialize_refused(tmp_path):
    """A verified-checksum artifact whose PAYLOAD isn't a serialized
    executable (a bad bake, a cosmic ray that kept the sha) still
    degrades to a miss with the reason attributed."""
    plane = CompilePlane(cache_dir=str(tmp_path))
    digest = "b" * 24
    key = plane.key_for(None, digest)
    plane.cache.write(
        key, bucket_key(None), digest, plane.fingerprint, plane.fp_hex,
        b"not a pickled executable",
    )
    assert plane.load(None, digest) is None
    assert plane.unsupported.get(aot.REASON_DESERIALIZE, 0) == 1


def test_breaker_open_routes_around_the_directory(tmp_path):
    """An open TIER_COMPILEPLANE breaker: loads are misses, stores are
    no-ops, nothing touches disk — the wave compiles in-process
    exactly as before the plane existed."""
    compiled, x = _tiny_compiled()
    plane = CompilePlane(cache_dir=str(tmp_path))
    digest = entry_digest("generic", False, {}, (x,))
    assert plane.store(None, digest, compiled) is not None
    cb.breaker(cb.TIER_COMPILEPLANE).force_open()
    fresh = CompilePlane(cache_dir=str(tmp_path))
    assert fresh.load(None, digest) is None  # artifact exists on disk
    assert fresh.misses == 1 and fresh.cache_hits == 0
    assert fresh.store(None, "c" * 24, compiled) is None
    assert len(fresh.cache) == 1  # the no-op store wrote nothing


def test_io_faults_trip_the_breaker_then_recover(tmp_path):
    """Repeated read faults (the resilience injection site) count as
    tier failures and trip the breaker open; a healthy probe closes
    it."""
    compiled, x = _tiny_compiled()
    plane = CompilePlane(cache_dir=str(tmp_path))
    digest = entry_digest("generic", False, {}, (x,))
    plane.store(None, digest, compiled)
    cb.configure(cb.TIER_COMPILEPLANE, failure_threshold=2,
                 recovery_s=0.0)
    fresh = CompilePlane(cache_dir=str(tmp_path))
    arm_fault("compileplane.read", times=2)
    assert fresh.load(None, digest) is None
    assert fresh.load(None, digest) is None
    assert cb.breaker(cb.TIER_COMPILEPLANE).state != cb.STATE_CLOSED
    disarm_faults()
    # recovery_s=0: the next attempt is the half-open probe; a healthy
    # read closes the breaker and the artifact loads again
    assert fresh.load(None, digest) is not None
    assert cb.breaker(cb.TIER_COMPILEPLANE).state == cb.STATE_CLOSED


# -- bucket mining -----------------------------------------------------------
def test_mine_buckets_corpus_union_and_dedupe(tmp_path):
    code_dir = tmp_path / "corpus"
    code_dir.mkdir()
    (code_dir / "writer.hex").write_text("0x" + WRITER)
    (code_dir / "writer_again.hex").write_text(WRITER)
    buckets = mine_buckets(corpus=[str(code_dir)])
    assert None in buckets  # the generic kernel always rides along
    spec = [b for b in buckets if b is not None]
    assert spec  # duplicate contracts dedupe to one bucket (+ union)
    keys = {json.dumps(bucket_key(b), sort_keys=True) for b in buckets}
    assert len(keys) == len(buckets)


def test_mine_buckets_routing_rows(tmp_path):
    phases = sp.phases_for(sp.signature_for(bytes.fromhex(WRITER)))
    rows = [
        {"features": {"phase_bucket": bucket_key(phases)}},
        {"features": {"phase_bucket_pruned": 3}},  # pre-plane record
        {"not": "a routing row"},
    ]
    path = tmp_path / "routing_features.jsonl"
    path.write_text(
        "\n".join(json.dumps(r) for r in rows) + "\nnot json\n"
    )
    buckets = mine_buckets(routing=[str(path)], include_generic=False,
                           include_union=False)
    assert buckets == [phases]


def test_routing_features_carry_full_bucket():
    """features_for emits the full phase_bucket dict the bake miner
    reads — live traffic is minable without a capture corpus."""
    from mythril_tpu.observe.routing import features_for

    feats = features_for(WRITER)
    bucket = feats.get("phase_bucket")
    assert isinstance(bucket, dict) and bucket["kind"] == "spec"
    assert phases_from_bucket(bucket) is not None


# -- baking + the fresh-process differential ---------------------------------
@pytest.fixture(scope="module")
def baked_pack(tmp_path_factory):
    """ONE real generic-kernel bake for the whole module (the compile
    is the expensive part; every consumer below only loads)."""
    pack_dir = str(tmp_path_factory.mktemp("pack"))
    reset_plane()
    clear_aot_generic()
    manifest = bake_service_pack(pack_dir, [None], **SHAPE)
    reset_plane()
    clear_aot_generic()
    return pack_dir, manifest


def test_bake_manifest_and_tools(baked_pack):
    pack_dir, manifest = baked_pack
    assert manifest["artifacts"] >= 1
    assert manifest["shape"]["n_lanes"] == (
        SHAPE["stripes"] * SHAPE["lanes_per_stripe"]
    )
    assert manifest["fingerprint_hex"] == fingerprint_hex()
    assert read_manifest(pack_dir)["buckets"] == [{"kind": "generic"}]

    listing = list_pack(pack_dir)
    assert listing["artifacts"] and listing["manifest"] is not None

    report = verify_pack(pack_dir)
    assert report["loadable"] >= 1 and report["refused"] == 0

    gced = gc_pack(pack_dir, capacity=64, drop_stale=True)
    assert gced["stale_dropped"] == 0 and gced["remaining"] >= 1


def test_pack_mount_preloads_and_wave_hits(baked_pack):
    pack_dir, _ = baked_pack
    plane = configure_plane(pack_dirs=(pack_dir,))
    mounted = plane.mount_packs()
    assert mounted["mounted"] >= 1 and mounted["refused"] == 0

    shape = read_manifest(pack_dir)["shape"]
    batch, table = _pack_arena(shape)
    digest = wave_entry_digest(
        batch, table, max_steps=shape["steps_per_wave"],
        track_coverage=True, donate=False,
    )
    assert plane.preloaded(None, digest)
    out, steps = wave_run(
        batch, table, max_steps=shape["steps_per_wave"],
        track_coverage=True, donate=False,
    )
    jax.block_until_ready(steps)
    # the pack answered: zero in-process compiles of the packed bucket
    assert generic_aot_stats()["compiles"] == 0
    assert plane.pack_hits + plane.mem_hits >= 1
    assert plane.hit_rate() > 0.0
    assert plane.stats()["kernel_pack_hit_rate"] > 0.0


def test_pack_loads_in_fresh_process_bit_identical(baked_pack):
    """The tentpole differential: a subprocess with a cold jit cache
    mounts the pack, runs a wave through the plane with ZERO compiles,
    and its results hash identically to this process's in-process
    baseline."""
    pack_dir, _ = baked_pack
    shape = read_manifest(pack_dir)["shape"]

    script = f"""
import hashlib, json, sys
import numpy as np
from mythril_tpu.compileplane.pack import read_manifest
from mythril_tpu.compileplane.plane import configure_plane
from mythril_tpu.laser.batch.run import generic_aot_stats, wave_run
from mythril_tpu.laser.batch.state import make_batch, make_code_table

pack = {pack_dir!r}
shape = read_manifest(pack)["shape"]
plane = configure_plane(pack_dirs=(pack,))
mounted = plane.mount_packs()
n = shape["n_lanes"]
batch = make_batch(
    n, code_ids=np.full((n,), shape["stripes"], np.int32),
    calldata=[b""] * n,
)
table = make_code_table(
    [bytes.fromhex({WRITER!r})] * (shape["stripes"] + 1),
    code_cap=shape["code_cap"],
)
out, steps = wave_run(batch, table, max_steps=shape["steps_per_wave"],
                      track_coverage=True, donate=False)
sha = hashlib.sha256()
sha.update(np.asarray(out.status).tobytes())
sha.update(np.asarray(out.pc).tobytes())
sha.update(np.asarray(out.storage_vals).tobytes())
print(json.dumps({{
    "mounted": mounted["mounted"],
    "compiles": generic_aot_stats()["compiles"],
    "steps": int(steps),
    "sha": sha.hexdigest(),
}}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    child = json.loads(proc.stdout.strip().splitlines()[-1])
    assert child["mounted"] >= 1
    assert child["compiles"] == 0  # the zero-cold-start contract

    # the in-process baseline over the SAME inputs, no plane at all
    batch, table = _pack_arena(shape, codes=[bytes.fromhex(WRITER)])
    out, steps = run(batch, table, max_steps=shape["steps_per_wave"],
                     track_coverage=True)
    sha = hashlib.sha256()
    sha.update(np.asarray(out.status).tobytes())
    sha.update(np.asarray(out.pc).tobytes())
    sha.update(np.asarray(out.storage_vals).tobytes())
    assert int(steps) == child["steps"]
    assert sha.hexdigest() == child["sha"]
