"""End-to-end detection-module tests on hand-assembled bytecode
(reference test strategy: golden e2e runs, scaled down to unit size)."""

import pytest

from mythril_tpu.analysis.security import fire_lasers, retrieve_callback_issues
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.disassembler.disassembly import Disassembly


class FakeContract:
    """Minimal contract model (stands in for EVMContract)."""

    def __init__(self, code, name="Test"):
        self.name = name
        self.disassembly = Disassembly(code)
        self.creation_code = None
        self.code = code


def analyze(code, tx_count=1, modules=None):
    contract = FakeContract(code)
    sym = SymExecWrapper(
        contract,
        address=0x1234,
        strategy="bfs",
        execution_timeout=90,
        create_timeout=30,
        transaction_count=tx_count,
        modules=modules,
    )
    return fire_lasers(sym, white_list=modules)


def swc_ids(issues):
    return {i.swc_id for i in issues}


def test_unprotected_selfdestruct_detected():
    # CALLER SELFDESTRUCT
    issues = analyze("33ff", modules=["AccidentallyKillable"])
    assert swc_ids(issues) == {"106"}
    issue = issues[0]
    assert issue.severity == "High"
    assert issue.transaction_sequence is not None


def test_ether_thief_detected():
    # send the whole balance to the caller:
    # PUSH1 0 x4, SELFBALANCE, CALLER, PUSH2 0xffff, CALL, POP, STOP
    issues = analyze("6000600060006000473361fffff15000", modules=["EtherThief"])
    assert "105" in swc_ids(issues)


def test_exception_state_detected():
    # branch on calldata: if word0 != 0 -> ASSERT_FAIL
    # PUSH1 0 CALLDATALOAD PUSH1 7 JUMPI STOP JUMPDEST INVALID(fe)
    issues = analyze("600035600757005bfe", modules=["Exceptions"])
    assert swc_ids(issues) == {"110"}


def test_tx_origin_detected():
    # branch on ORIGIN == CALLER: ORIGIN CALLER EQ PUSH1 7 JUMPI STOP JUMPDEST STOP
    issues = analyze("3233146007" + "57005b00", modules=["TxOrigin"])
    assert swc_ids(issues) == {"115"}


def test_clean_contract_yields_no_issues():
    # PUSH1 1 PUSH1 0 SSTORE STOP: plain storage write, no issue
    issues = analyze("6001600055600060015500")
    assert issues == []


def test_delegatecall_to_calldata_address_detected():
    # DELEGATECALL to an address read from calldata:
    # PUSH1 0(outsz) PUSH1 0(outoff) PUSH1 0(insz) PUSH1 0(inoff)
    # PUSH1 0 CALLDATALOAD (to) PUSH2 0xffff (gas) DELEGATECALL POP STOP
    issues = analyze(
        "6000600060006000" + "600035" + "61ffff" + "f45000",
        modules=["ArbitraryDelegateCall"],
    )
    assert swc_ids(issues) == {"112"}
