"""Hybrid concolic fuzzing tests: device execution + solver-driven
branch flipping must crack magic-value gates that random inputs cannot
(each gate has ~2^-256 random probability)."""

import pytest

from mythril_tpu.analysis.hybrid_fuzz import HybridFuzzer


def two_gate_contract() -> str:
    """word0 == 0x42 guards gate 1; word1 == 0x1337 guards gate 2;
    passing both reaches SSTORE(0, 0xbeef)."""
    code = bytearray()
    code += bytes.fromhex("600035")
    code += bytes.fromhex("6042")
    code += bytes.fromhex("14")
    d1 = len(code) + 3 + 1
    code += bytes([0x60, d1, 0x57, 0x00])
    code += bytes([0x5B])
    code += bytes.fromhex("602035")
    code += bytes.fromhex("611337")
    code += bytes.fromhex("14")
    d2 = len(code) + 3 + 1
    code += bytes([0x60, d2, 0x57, 0x00])
    code += bytes([0x5B])
    code += bytes.fromhex("61beef60005500")
    return code.hex()


def test_cracks_sequential_magic_gates():
    fuzzer = HybridFuzzer(
        two_gate_contract(),
        calldata_len=64,
        lanes_per_generation=16,
        max_generations=6,
        seed=3,
    )
    result = fuzzer.run()
    # all four branch directions of the two gates were executed
    pcs = {pc for pc, _ in result["covered_branches"]}
    assert len(pcs) == 2
    assert all(
        (pc, flag) in result["covered_branches"]
        for pc in pcs
        for flag in (True, False)
    )
    # the double-guarded write was reached with the exact value
    assert result["storage_writes"].get("0x0") == ["0xbeef"]


def test_terminates_without_frontier():
    # straight-line contract: one generation, no flips possible
    fuzzer = HybridFuzzer(
        "6001600055600060015500",
        calldata_len=8,
        lanes_per_generation=4,
        max_generations=4,
        seed=1,
    )
    result = fuzzer.run()
    assert result["generations"] == 1
    assert result["covered_branches"] == []
    assert result["storage_writes"].get("0x0") == ["0x1"]


def test_finds_concrete_assert_violation_behind_gate():
    """An INVALID (assert) guarded by a 256-bit magic word: the fuzzer
    must produce the concrete calldata that triggers it."""
    code = bytearray()
    code += bytes.fromhex("600035")      # CALLDATALOAD(0)
    code += bytes.fromhex("60a7")        # PUSH1 0xa7
    code += bytes.fromhex("14")          # EQ
    dest = len(code) + 3 + 1
    code += bytes([0x60, dest, 0x57, 0x00])  # JUMPI; STOP
    code += bytes([0x5B, 0xFE])          # JUMPDEST; INVALID

    fuzzer = HybridFuzzer(
        code.hex(),
        calldata_len=32,
        lanes_per_generation=8,
        max_generations=4,
        seed=11,
    )
    result = fuzzer.run()
    witnesses = result["triggers"].get("assert-violation", [])
    assert witnesses, "assert violation not triggered"
    # the witness really carries the gate value in word 0
    assert int(witnesses[0], 16) == 0xA7


@pytest.mark.slow
def test_real_contract_assert_triggers():
    """On the reference's compiled exceptions contract the loop must
    produce concrete calldata triggering real assert violations."""
    from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES

    src = GOLDEN_FIXTURES / "exceptions.sol.o"
    if not src.is_file():
        pytest.skip("fixture bytecode absent")

    fuzzer = HybridFuzzer(
        src.read_text().strip(),
        calldata_len=36,
        lanes_per_generation=32,
        max_generations=6,
        flips_per_generation=12,
        seed=5,
    )
    result = fuzzer.run()
    assert result["triggers"].get("assert-violation"), "no assert triggers found"
    assert len(result["covered_branches"]) > 20
