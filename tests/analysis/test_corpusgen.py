"""Benchmark-corpus synthesis (analysis/corpusgen.py).

The synthesized corpus is the stand-in for BASELINE config 3's
1k-contract SWC corpus; these tests pin the properties the benchmark's
honesty rests on: replicas are deterministic, structure-preserving
(same instruction skeleton, so they exercise the same code paths), and
genuinely distinct (different selectors/constants, so no work dedups
across replicas).
"""

import random

import pytest

from mythril_tpu.analysis.corpusgen import (
    _check_skeleton,
    load_fixtures,
    mutate_constants,
    synth_corpus,
)
from mythril_tpu.disassembler.disassembly import Disassembly

FAMILIES = load_fixtures()
pytestmark = pytest.mark.skipif(
    not FAMILIES, reason="reference fixture corpus not mounted"
)


def test_deterministic():
    assert synth_corpus(40) == synth_corpus(40)
    # a different seed changes the mutants but not the originals
    other = synth_corpus(40, seed=7)
    assert other != synth_corpus(40)
    assert [row for row in other if row[2].endswith("#0")] == [
        row for row in synth_corpus(40) if row[2].endswith("#0")
    ]


def test_replica_zero_is_the_original():
    corpus = {name: code for code, _, name in synth_corpus(26)}
    for name, code_hex in FAMILIES:
        assert corpus[f"{name}#0"] == code_hex


@pytest.mark.parametrize("name,code_hex", FAMILIES)
def test_skeleton_preserved(name, code_hex):
    orig = bytes.fromhex(code_hex)
    mutant = mutate_constants(orig, random.Random(f"t:{name}"))
    assert _check_skeleton(orig, mutant)
    d0, d1 = Disassembly(code_hex), Disassembly(mutant.hex())
    assert [i["opcode"] for i in d0.instruction_list] == [
        i["opcode"] for i in d1.instruction_list
    ]


def test_replicas_are_distinct_work():
    """No two replicas of a family share selectors or full bytecode —
    the property that makes N replicas N units of analyzer work."""
    corpus = synth_corpus(13 * 4)
    by_family = {}
    for code, _, name in corpus:
        by_family.setdefault(name.split("#")[0], []).append(code)
    mutated_selector_families = 0
    for family, codes in by_family.items():
        assert len(set(codes)) == len(codes), family
        selectors = [frozenset(Disassembly(c).func_hashes) for c in codes]
        if len(set(selectors)) == len(selectors):
            mutated_selector_families += 1
    # every family with a dispatcher must yield distinct selector sets
    assert mutated_selector_families >= 10


def test_corpus_size_and_shape():
    corpus = synth_corpus(208)
    assert len(corpus) == 208
    codes, creations, names = zip(*corpus)
    assert len(set(names)) == 208
    assert all(c == "" for c in creations)
    assert all(len(c) >= 8 and "0x" not in c for c in codes)
