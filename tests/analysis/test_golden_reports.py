"""Full-report golden tests over the reference fixture corpus.

Reference parity: the reference diffs complete CLI output against
committed expected files (tests/cmd_line_test.py:17-47 +
tests/testdata/outputs_expected/). These tests replace the round-2
membership asserts ("110 in swc_ids") with exact-set comparisons: the
complete canonical issue list — every address, swc id, title,
severity, function, description, and transaction-sequence input — must
match the committed goldens, produced by the same pinned
`golden_corpus_run()` configuration.

Regenerate deliberately with `python tools/make_goldens.py` (CPU
backend) when behavior changes on purpose.
"""

import json
from pathlib import Path

import pytest

from mythril_tpu.analysis.goldens import (
    GOLDEN_FIXTURES,
    canonical_issues,
)

GOLDEN_DIR = Path(__file__).resolve().parent.parent / "testdata" / "goldens"

if not GOLDEN_FIXTURES.is_dir():
    pytest.skip("reference fixtures not available", allow_module_level=True)

FIXTURE_NAMES = sorted(f.stem for f in GOLDEN_FIXTURES.glob("*.sol.o"))


@pytest.fixture(scope="module")
def corpus_results():
    from mythril_tpu.analysis.goldens import golden_corpus_run

    return dict(golden_corpus_run())


@pytest.mark.slow
def test_every_fixture_has_a_golden():
    """Goldens are committed artifacts: a fixture without one (or a
    stray golden without a fixture) is a hard failure, not a silent
    skip — missing coverage must be indistinguishable from red."""
    goldens = sorted(
        p.name[: -len(".issues.json")]
        for p in GOLDEN_DIR.glob("*.issues.json")
    )
    assert goldens == FIXTURE_NAMES, (
        "goldens out of sync with the fixture corpus — run "
        "`python tools/make_goldens.py` and commit the result"
    )


@pytest.mark.slow
@pytest.mark.parametrize("name", FIXTURE_NAMES)
def test_full_issue_report_matches_golden(name, corpus_results):
    golden = GOLDEN_DIR / f"{name}.issues.json"
    assert golden.is_file(), (
        f"no golden for {name} — run `python tools/make_goldens.py`"
    )
    result = corpus_results[name]
    assert result["error"] is None, result["error"]
    expected = json.loads(golden.read_text())
    actual = canonical_issues(result["issues"])
    assert actual == expected, (
        f"{name}: issue report drifted from golden "
        f"({len(actual)} vs {len(expected)} issues)"
    )
