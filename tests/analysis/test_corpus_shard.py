"""Multi-host corpus sharding (analysis/corpus.py corpus_shard — the
DCN axis of SURVEY §2.4's per-contract-loop mapping)."""

import pytest

from mythril_tpu.analysis.corpus import corpus_shard


def rows(n):
    return [(f"60{i:02x}00", "", f"c{i}") for i in range(n)]


def test_partition_is_complete_and_disjoint():
    corpus = rows(40)
    shards = [corpus_shard(corpus, i, 4) for i in range(4)]
    merged = [row for shard in shards for row in shard]
    assert sorted(merged) == sorted(corpus)
    names = [set(r[2] for r in s) for s in shards]
    for i in range(4):
        for j in range(i + 1, 4):
            assert not (names[i] & names[j])


def test_partition_is_content_stable():
    """Hosts must agree on the partition regardless of how each one
    enumerates the inputs."""
    corpus = rows(24)
    shuffled = list(reversed(corpus))
    for i in range(3):
        assert sorted(corpus_shard(corpus, i, 3)) == sorted(
            corpus_shard(shuffled, i, 3)
        )


def test_single_shard_is_identity():
    corpus = rows(5)
    assert corpus_shard(corpus, 0, 1) == corpus


def test_bad_index_rejected():
    with pytest.raises(ValueError):
        corpus_shard(rows(3), 3, 3)


def test_cli_flag_parses_and_filters(tmp_path, capsys):
    """`--corpus-shard 0/2` + `1/2` over the same inputs split the
    contracts; an empty shard exits cleanly as a no-findings run."""
    from mythril_tpu.interfaces.cli import _apply_corpus_shard

    class Contract:
        def __init__(self, name, code):
            self.name, self.code = name, code

    class Dis:
        def __init__(self):
            self.contracts = [Contract(f"c{i}", f"60{i:02x}00") for i in range(8)]

    class Args:
        outform = "text"
        corpus_shard = None

    sizes = []
    for spec in ("0/2", "1/2"):
        dis = Dis()
        args = Args()
        args.corpus_shard = spec
        _apply_corpus_shard(dis, args)
        sizes.append(len(dis.contracts))
    assert sum(sizes) == 8
    assert all(s < 8 for s in sizes)
