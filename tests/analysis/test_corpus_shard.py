"""Multi-host corpus sharding (analysis/corpus.py corpus_shard — the
DCN axis of SURVEY §2.4's per-contract-loop mapping)."""

import pytest

from mythril_tpu.analysis.corpus import corpus_shard


def rows(n):
    return [(f"60{i:02x}00", "", f"c{i}") for i in range(n)]


def test_partition_is_complete_and_disjoint():
    corpus = rows(40)
    shards = [corpus_shard(corpus, i, 4) for i in range(4)]
    merged = [row for shard in shards for row in shard]
    assert sorted(merged) == sorted(corpus)
    names = [set(r[2] for r in s) for s in shards]
    for i in range(4):
        for j in range(i + 1, 4):
            assert not (names[i] & names[j])


def test_partition_is_content_stable():
    """Hosts must agree on the partition regardless of how each one
    enumerates the inputs."""
    corpus = rows(24)
    shuffled = list(reversed(corpus))
    for i in range(3):
        assert sorted(corpus_shard(corpus, i, 3)) == sorted(
            corpus_shard(shuffled, i, 3)
        )


def test_single_shard_is_identity():
    corpus = rows(5)
    assert corpus_shard(corpus, 0, 1) == corpus


def test_bad_index_rejected():
    with pytest.raises(ValueError):
        corpus_shard(rows(3), 3, 3)


class _Contract:
    def __init__(self, name, code):
        self.name, self.code = name, code


class _Dis:
    def __init__(self, n=8):
        self.contracts = [_Contract(f"c{i}", f"60{i:02x}00") for i in range(n)]


class _Args:
    outform = "json"
    corpus_shard = None


def test_cli_flag_parses_and_filters():
    """`--corpus-shard 0/2` + `1/2` over the same inputs split the
    contracts between the two hosts."""
    from mythril_tpu.interfaces.cli import _apply_corpus_shard

    sizes = []
    for spec in ("0/2", "1/2"):
        dis = _Dis()
        args = _Args()
        args.corpus_shard = spec
        emptied = _apply_corpus_shard(dis, args)
        assert emptied == (not dis.contracts)
        sizes.append(len(dis.contracts))
    assert sum(sizes) == 8
    assert all(s < 8 for s in sizes)


def test_cli_empty_shard_is_clean_but_empty_input_is_not():
    """Sharding a 1-contract corpus across many hosts empties most
    shards — those are clean no-findings runs (True). A contract list
    that was ALREADY empty is an input error and must not be masked
    by the shard flag (False, list untouched)."""
    from mythril_tpu.interfaces.cli import _apply_corpus_shard

    lonely = _Dis(n=1)
    probe_args = _Args()
    probe_args.corpus_shard = "0/2"
    _apply_corpus_shard(lonely, probe_args)
    home_shard = 0 if lonely.contracts else 1

    dis = _Dis(n=1)
    args = _Args()
    args.corpus_shard = f"{1 - home_shard}/2"
    assert _apply_corpus_shard(dis, args) is True
    assert dis.contracts == []

    empty = _Dis(n=0)
    args = _Args()
    args.corpus_shard = "0/2"
    assert _apply_corpus_shard(empty, args) is False


def test_cli_empty_shard_report_honors_outform():
    """The empty-shard early exit must emit a parseable report in the
    requested outform so multi-host merge scripts never choke."""
    import json

    from mythril_tpu.analysis.report import Report

    report = json.loads(Report().as_json())
    assert report["success"] is True and report["issues"] == []
