"""Corpus-parallel analysis tests."""

import pytest

from mythril_tpu.analysis.corpus import analyze_corpus

CONTRACTS = [
    ("33ff", "", "Killable"),  # CALLER SELFDESTRUCT -> SWC-106
    ("6001600055600060015500", "", "Clean"),  # plain storage write
    ("600035600757005bfe", "", "Asserting"),  # reachable INVALID -> SWC-110
]


def swc_ids(result):
    return {issue["swc-id"] for issue in result["issues"]}


@pytest.mark.parametrize("processes", [1, 2])
def test_corpus_analysis(processes):
    results = analyze_corpus(
        CONTRACTS,
        transaction_count=1,
        execution_timeout=90,
        processes=processes,
    )
    by_name = {r["name"]: r for r in results}
    assert by_name["Killable"]["error"] is None
    assert "106" in swc_ids(by_name["Killable"])
    assert swc_ids(by_name["Clean"]) == set()
    assert "110" in swc_ids(by_name["Asserting"])


def test_corpus_contains_worker_errors_not_raises():
    # invalid hex must come back as a contained per-contract error
    results = analyze_corpus(
        [("zz-not-hex", "", "Broken")], transaction_count=1, processes=1
    )
    assert results[0]["error"] is not None


#: gated assert: INVALID only when calldata byte 0 == 0x42 — a host
#: walk at a tiny budget won't prove it, the device wave will
_GATED_FAIL = bytes(
    [0x60, 0x00, 0x35,  # PUSH1 0; CALLDATALOAD
     0x60, 0xF8, 0x1C,  # PUSH1 248; SHR
     0x60, 0x42, 0x14,  # PUSH1 0x42; EQ
     0x60, 0x0D, 0x57,  # PUSH1 13; JUMPI
     0x00, 0x5B, 0xFE]  # STOP; JUMPDEST; ASSERT_FAIL
).hex()

_DEVICE_CONTRACTS = [
    ("600035600757005bfe", "", "PlainAssert"),
    (_GATED_FAIL, "", "GatedAssert"),
    ("33ff", "", "Killable"),
]


def test_corpus_device_prepass_feeds_workers():
    """The parent's striped device exploration produces per-contract
    outcomes that pooled workers consume: witnesses arrive as issues
    (with provenance when the host walk missed them) and the prepass
    counters ride along in each result (VERDICT r2 task 2)."""
    contracts = [
        ("600035600757005bfe", "", "PlainAssert"),
        (_GATED_FAIL, "", "GatedAssert"),
    ]
    results = analyze_corpus(
        contracts,
        transaction_count=1,
        execution_timeout=60,
        processes=2,
        use_device=True,  # force the device axis on the CPU mesh
        device_budget_s=30.0,
    )
    by_name = {r["name"]: r for r in results}
    for r in results:
        assert r["error"] is None, r["error"]
        assert r["device_prepass"] is not None
        assert r["device_prepass"]["device_steps"] > 0
    assert "110" in swc_ids(by_name["PlainAssert"])
    assert "110" in swc_ids(by_name["GatedAssert"])


def _assert_device_corpus_results(results):
    by_name = {r["name"]: r for r in results}
    for r in results:
        assert r["error"] is None, r["error"]
    assert "110" in swc_ids(by_name["PlainAssert"])
    assert "110" in swc_ids(by_name["GatedAssert"])
    assert "106" in swc_ids(by_name["Killable"])
    # the prepass outcome must have been folded into the results
    assert any(r.get("device_prepass") for r in results)


def test_corpus_single_core_device_prepass_first(monkeypatch):
    """Single-process on a 1-core host: the prepass runs FIRST,
    uncontended, and its final outcome is injected into every
    analysis (the overlap needs a second core to pay)."""
    import mythril_tpu.analysis.corpus as C

    monkeypatch.setattr(C, "_effective_cpus", lambda: 1)
    results = analyze_corpus(
        _DEVICE_CONTRACTS,
        transaction_count=1,
        execution_timeout=60,
        processes=1,
        use_device=True,  # force the device axis on the CPU mesh
        device_budget_s=30.0,
    )
    _assert_device_corpus_results(results)


def test_corpus_overlapped_single_process_device(monkeypatch):
    """Single-process on a multi-core host: the prepass runs in a
    thread overlapped with the host analyses (both sides serialized
    on HOST_SYMBOLIC_LOCK), cheap contracts are scheduled into the
    overlap window, witnesses still reach the results, and
    per-contract errors stay contained."""
    import mythril_tpu.analysis.corpus as C

    monkeypatch.setattr(C, "_effective_cpus", lambda: 2)
    results = analyze_corpus(
        _DEVICE_CONTRACTS,
        transaction_count=1,
        execution_timeout=60,
        processes=1,
        use_device=True,  # force the overlapped branch on the CPU mesh
        device_budget_s=30.0,
    )
    _assert_device_corpus_results(results)


def test_prepass_budget_is_monotone_at_the_overlap_threshold():
    """Crossing OVERLAP_MIN_CORPUS must never SHRINK the prepass
    budget (review regression: 31 contracts got 30s while 32 got
    16s before the large-corpus floor landed)."""
    from mythril_tpu.analysis.corpus import (
        OVERLAP_MIN_CORPUS,
        resolve_prepass_budget_s,
    )

    budgets = [
        resolve_prepass_budget_s(n)
        for n in range(1, OVERLAP_MIN_CORPUS + 32)
    ]
    assert all(b2 >= b1 for b1, b2 in zip(budgets, budgets[1:]))


def test_yield_lock_only_when_wanted(monkeypatch):
    """OverlappedPrepass.yield_lock hands the lock over only while a
    flip burst is actually waiting — an unconditional sleep taxed
    every analysis of a large corpus (round-4 lock-wanted handshake).
    time.sleep is stubbed so the contract (sleep called iff the lock
    is wanted) is pinned without wall-clock sensitivity."""
    import mythril_tpu.analysis.corpus as corpus_mod
    from mythril_tpu.analysis.corpus import OverlappedPrepass

    slept = []
    monkeypatch.setattr(corpus_mod.time, "sleep", slept.append)

    pre = OverlappedPrepass.__new__(OverlappedPrepass)

    class AliveThread:
        def is_alive(self):
            return True

    class Wanted:
        def __init__(self, value):
            self.value = value

        def is_set(self):
            return self.value

    pre._thread = AliveThread()
    pre._lock_wanted = Wanted(False)
    pre.yield_lock()
    assert slept == []  # no yield when nobody is waiting

    pre._lock_wanted = Wanted(True)
    pre.yield_lock()
    assert len(slept) == 1
