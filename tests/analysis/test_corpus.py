"""Corpus-parallel analysis tests."""

import pytest

from mythril_tpu.analysis.corpus import analyze_corpus

CONTRACTS = [
    ("33ff", "", "Killable"),  # CALLER SELFDESTRUCT -> SWC-106
    ("6001600055600060015500", "", "Clean"),  # plain storage write
    ("600035600757005bfe", "", "Asserting"),  # reachable INVALID -> SWC-110
]


def swc_ids(result):
    return {issue["swc-id"] for issue in result["issues"]}


@pytest.mark.parametrize("processes", [1, 2])
def test_corpus_analysis(processes):
    results = analyze_corpus(
        CONTRACTS,
        transaction_count=1,
        execution_timeout=90,
        processes=processes,
    )
    by_name = {r["name"]: r for r in results}
    assert by_name["Killable"]["error"] is None
    assert "106" in swc_ids(by_name["Killable"])
    assert swc_ids(by_name["Clean"]) == set()
    assert "110" in swc_ids(by_name["Asserting"])


def test_corpus_contains_worker_errors_not_raises():
    # invalid hex must come back as a contained per-contract error
    results = analyze_corpus(
        [("zz-not-hex", "", "Broken")], transaction_count=1, processes=1
    )
    assert results[0]["error"] is not None
