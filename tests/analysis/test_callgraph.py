"""Cross-contract static linker suite: call-site provenance goldens,
SCC-aware escape widening, proxy pairing + storage-collision diff,
the linked-fingerprint invalidation differential through the verdict
store, the `myth graph` JSON golden, the four link lint checks, and
the routing-schema v3 -> v4 back-compat.

Tier-1 via the `linker` marker (tox -e linker runs it alone).
Host-only: the linker never imports jax.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from mythril_tpu.analysis.corpusgen import (
    cross_call_pair,
    minimal_proxy,
    proxy_pair,
    synth_bench_corpus,
)
from mythril_tpu.analysis.static import (
    LINT_CHECKS,
    LINT_SCHEMA_VERSION,
    analyze_bytecode,
    summary_for,
)
from mythril_tpu.analysis.static.callgraph import (
    EIP1967_IMPL_SLOT,
    LINK_CHECKS,
    MINIMAL_PROXY_CALL_PC,
    PROV_CONSTANT,
    PROV_MINIMAL_PROXY,
    PROV_PROXY_SLOT,
    PROV_STORAGE_SLOT,
    PROV_TAINTED,
    implementation_from_init_code,
    minimal_proxy_target,
)
from mythril_tpu.analysis.static.linkset import (
    GRAPH_SCHEMA_VERSION,
    LinkSet,
    address_from_name,
    link_corpus,
)
from mythril_tpu.analysis.static.taint import TAINT_ANY, TAINT_ATTACKER

pytestmark = pytest.mark.linker

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _edges(linkset):
    return linkset.resolve()["edges"]


def _linkset_of(rows):
    return link_corpus(rows)


def _checks(summary):
    return {f["check"] for f in summary.findings()}


# -- provenance goldens ------------------------------------------------------
def test_provenance_proxy_slot():
    """An EIP-1967 slot-read DELEGATECALL resolves through the runtime
    slot binding to the implementation declared at the book address."""
    rows = proxy_pair(seed=0)
    linkset = _linkset_of(rows)
    (edge,) = _edges(linkset)
    assert edge["kind"] == "DELEGATECALL"
    assert edge["provenance"] == PROV_PROXY_SLOT
    assert edge["resolved"] is True
    assert edge["target_address"] == "0x" + rows[1][2].split("@0x")[1]
    proxy_node = linkset.nodes[edge["caller"]]
    assert proxy_node.proxy_kind == "eip1967"
    assert proxy_node.upgradeable  # mounts upgradeTo + writes the slot
    assert linkset.stats()["resolve_rate"] == 1.0


def test_provenance_minimal_proxy():
    """An EIP-1167 forwarder is recognized whole-code: the baked
    implementation address resolves without any dataflow."""
    rows = minimal_proxy(seed=0)
    linkset = _linkset_of(rows)
    (edge,) = _edges(linkset)
    assert edge["kind"] == "DELEGATECALL"
    assert edge["provenance"] == PROV_MINIMAL_PROXY
    assert edge["pc"] == MINIMAL_PROXY_CALL_PC
    assert edge["resolved"] is True
    assert linkset.nodes[edge["caller"]].minimal_proxy is True
    # the whole-code matcher round-trips the literal
    code = bytes.fromhex(rows[0][0])
    assert minimal_proxy_target(code) == int(edge["target_address"], 16)
    assert minimal_proxy_target(bytes.fromhex(rows[1][0])) is None


def test_provenance_constant():
    """A PUSH20-literal CALL target is `constant` and resolves through
    the address book."""
    rows = cross_call_pair(seed=0)
    linkset = _linkset_of(rows)
    (edge,) = _edges(linkset)
    assert edge["kind"] == "CALL"
    assert edge["provenance"] == PROV_CONSTANT
    assert edge["resolved"] is True
    assert edge["callee"] in linkset.nodes
    assert address_from_name(rows[1][2]) == int(edge["target_address"], 16)


def test_provenance_tainted():
    """A CALLDATALOAD-fed DELEGATECALL target is `tainted` and can
    never resolve (any address is reachable)."""
    # PUSH1 0 x4; CALLDATALOAD(0); PUSH2 gas; DELEGATECALL; POP; STOP
    code_hex = "6000600060006000" + "600035" + "61ffff" + "f45000"
    summary = analyze_bytecode(code_hex)
    (site,) = summary.link.call_sites
    assert site.provenance == PROV_TAINTED
    assert site.target_taint & TAINT_ATTACKER
    linkset = LinkSet()
    linkset.add("t", bytes.fromhex(code_hex), summary)
    (edge,) = _edges(linkset)
    assert edge["resolved"] is False


def test_provenance_storage_slot():
    """A target read from an UNNAMED storage slot stays `storage-slot`
    (not proxy-slot): the slot is pinned, the value is not."""
    # PUSH1 0 x4; SLOAD(5); PUSH2 gas; DELEGATECALL; POP; STOP
    summary = analyze_bytecode(
        "6000600060006000" + "600554" + "61ffff" + "f45000"
    )
    (site,) = summary.link.call_sites
    assert site.provenance == PROV_STORAGE_SLOT
    assert site.slot == 5


def test_implementation_from_init_code():
    """The constructor-wiring matcher the watcher shares: PUSH20 addr
    then PUSH32 named-impl-slot (SSTORE tail) -> the address; plain
    init code -> None; Gnosis slot 0 deliberately unmatched."""
    addr = 0xABC
    wired = (
        "73" + f"{addr:040x}" + "7f" + f"{EIP1967_IMPL_SLOT:064x}" + "55"
    )
    assert implementation_from_init_code(wired) == addr
    assert implementation_from_init_code("0x" + wired) == addr
    assert implementation_from_init_code("600160005500") is None
    assert implementation_from_init_code("") is None
    # slot 0 (Gnosis) is far too common in init code to be a wiring
    slot0 = "73" + f"{addr:040x}" + "7f" + f"{0:064x}" + "55"
    assert implementation_from_init_code(slot0) is None


# -- SCC widening + closure problems ----------------------------------------
def test_cycle_widens_escape_and_names_link_cycle():
    """A two-contract call cycle: both members widen to TAINT_ANY and
    every selector whose closure enters the cycle gets `link-cycle`
    instead of a linked fingerprint — it never silently fingerprints."""
    caller_a = cross_call_pair(seed=0)[0]
    caller_b = cross_call_pair(seed=1)[0]
    target_a = address_from_name(cross_call_pair(seed=0)[1][2])
    target_b = address_from_name(cross_call_pair(seed=1)[1][2])
    # a's baked target resolves to b, b's to a: a 2-cycle
    rows = [
        (caller_a[0], "", f"a@0x{target_b:040x}"),
        (caller_b[0], "", f"b@0x{target_a:040x}"),
    ]
    linkset = _linkset_of(rows)
    data = linkset.resolve()
    assert len(data["cyclic"]) == 2
    for ch in linkset.nodes:
        escapes = data["escapes"][ch]
        sel = next(s for s in escapes if s != "*")
        assert escapes[sel]["mask"] == TAINT_ANY
        assert escapes[sel]["widened"] is True
        fps, problems = linkset.linked_fingerprints(ch)
        assert problems.get(sel) == "link-cycle"
        assert sel not in fps
    assert data["stats"]["escape_widened"] >= 2


def test_unresolved_edge_names_link_unresolved():
    """The caller WITHOUT its callee in the corpus: the edge stays
    unresolved and the selector's fingerprint is replaced by the
    `link-unresolved` problem; adding the callee repairs both."""
    rows = cross_call_pair(seed=2)
    caller_only = _linkset_of(rows[:1])
    (edge,) = _edges(caller_only)
    assert edge["resolved"] is False
    ch = edge["caller"]
    fps, problems = caller_only.linked_fingerprints(ch)
    sel = edge["selector"]
    assert problems.get(sel) == "link-unresolved"
    assert sel not in fps
    whole = _linkset_of(rows)
    fps2, problems2 = whole.linked_fingerprints(ch)
    assert problems2 == {}
    assert sel in fps2


def test_escape_mask_carries_attacker_args():
    """The cross-call caller CALLDATACOPYs calldata into call input:
    its selector's escape mask carries the ATTACKER bit, and the
    post-call MLOAD guard flags return_to_guard."""
    rows = cross_call_pair(seed=0)
    linkset = _linkset_of(rows)
    data = linkset.resolve()
    (edge,) = _edges(linkset)
    row = data["escapes"][edge["caller"]][edge["selector"]]
    assert row["mask"] & TAINT_ATTACKER
    assert row["widened"] is False
    assert row.get("return_to_guard") is True


# -- proxy pairing + storage collision --------------------------------------
def test_proxy_pair_and_collision_positive():
    linkset = _linkset_of(proxy_pair(seed=1, collide=True))
    data = linkset.resolve()
    (pair,) = data["pairs"]
    assert pair["kind"] == "eip1967"
    assert pair["upgradeable"] is True
    (collision,) = data["collisions"]
    assert collision["proxy"] == pair["proxy"]
    assert collision["slots"] == ["0x0"]
    assert any(
        f["check"] == "proxy-storage-collision" for f in linkset.findings()
    )


def test_proxy_pair_collision_negative():
    """Disjoint slots (and the named proxy slots themselves) never
    collide — the diff subtracts the slots CHOSEN not to clash."""
    linkset = _linkset_of(proxy_pair(seed=2, collide=False))
    data = linkset.resolve()
    assert len(data["pairs"]) == 1
    assert data["collisions"] == []
    assert not any(
        f["check"] == "proxy-storage-collision" for f in linkset.findings()
    )


def test_arena_plan_colocates_pair():
    linkset = _linkset_of(proxy_pair(seed=0))
    plan = linkset.arena_plan()
    (edge,) = _edges(linkset)
    assert plan[edge["caller"]] == [edge["callee"]]
    assert plan[edge["callee"]] == []


# -- linked fingerprints: the upgrade differential --------------------------
def test_linked_fingerprint_moves_only_forward_selector():
    """The unit half of the acceptance pin: swap the implementation
    behind an unchanged proxy — the proxy's base code (hence base
    fingerprints) is identical, and ONLY the forwarding selector's
    linked fingerprint moves; the admin selector's stays put."""
    before = _linkset_of(proxy_pair(seed=3, variant=0))
    after = _linkset_of(proxy_pair(seed=3, variant=1))
    proxy_ch = next(
        ch for ch, node in before.nodes.items() if node.is_proxy
    )
    assert proxy_ch in after.nodes  # proxy bytecode unchanged
    fps_before, prob_before = before.linked_fingerprints(proxy_ch)
    fps_after, prob_after = after.linked_fingerprints(proxy_ch)
    assert prob_before == prob_after == {}
    assert set(fps_before) == set(fps_after)
    moved = [s for s in fps_before if fps_before[s] != fps_after[s]]
    forward = f"0x{(0xCA11AB1E + 3) & 0xFFFFFFFF:08x}"
    assert moved == [forward]
    assert fps_before["0x3659cfe6"] == fps_after["0x3659cfe6"]


def test_store_linked_invalidation_differential(tmp_path):
    """THE acceptance differential, end to end through the verdict
    store: run 1 banks proxy+impl verdicts (with linked fingerprints);
    run 2 swaps the implementation behind the UNCHANGED proxy at the
    same deployment address. The proxy must settle incrementally —
    re-analyzing only the forwarding selector whose callee closure
    moved, banking the admin selector — and a third identical run must
    settle both rows as exact hits (never a stale verdict)."""
    from mythril_tpu.analysis.corpus import analyze_corpus
    from mythril_tpu.store import close_stores, open_store

    kw = dict(execution_timeout=8, processes=1, use_device=False)
    store_dir = str(tmp_path / "vstore")
    rows_v0 = proxy_pair(seed=5, variant=0)
    rows_v1 = proxy_pair(seed=5, variant=1)
    assert rows_v0[0] == rows_v1[0]  # the proxy row is byte-identical
    try:
        cold_proxy = analyze_corpus(
            [rows_v1[0]], store=False, **kw
        )[0]
        first = analyze_corpus(rows_v0, store_dir=store_dir, **kw)
        assert all(r["complete"] for r in first)
        assert not any(r.get("store_hit") for r in first)
        store = open_store(store_dir)
        assert len(store) == 2
        # the banked proxy entry carries the linked fingerprints
        import hashlib

        from mythril_tpu.analysis.static import (
            analysis_config_fingerprint,
        )

        proxy_hash = hashlib.sha256(
            bytes.fromhex(rows_v0[0][0])
        ).hexdigest()
        config_fp = analysis_config_fingerprint(
            transaction_count=2, create_timeout=10
        )
        entry = store.get(proxy_hash, config_fp)
        assert entry is not None and entry.linked_fingerprints

        second = analyze_corpus(rows_v1, store_dir=store_dir, **kw)
        proxy_res, impl_res = second
        assert proxy_res["store_incremental"] is True
        assert proxy_res["store"]["linked"] is True
        forward = f"0x{(0xCA11AB1E + 5) & 0xFFFFFFFF:08x}"
        assert proxy_res["store"]["changed_selectors"] == [forward]
        assert "0x3659cfe6" in proxy_res["store"]["unchanged_selectors"]
        # issue parity with a cold full run of the (unchanged) proxy
        assert sorted(
            (i.get("address"), i.get("swc-id"))
            for i in proxy_res["issues"]
        ) == sorted(
            (i.get("address"), i.get("swc-id"))
            for i in cold_proxy["issues"]
        )
        # the NEW implementation is a fresh codehash: full analysis
        assert not impl_res.get("store_hit")
        assert not impl_res.get("store_incremental")

        third = analyze_corpus(rows_v1, store_dir=store_dir, **kw)
        assert all(r.get("store_hit") for r in third)
        # routing sees the linked route
        from mythril_tpu.observe.routing import outcome_for

        assert outcome_for(proxy_res)["route"] == "store-incremental"
    finally:
        close_stores()


# -- myth graph CLI ---------------------------------------------------------
def test_myth_graph_json_golden(tmp_path):
    """`myth graph DIR --json` resolves every constant / proxy-slot /
    minimal-proxy edge across the fixture pairs, sub-second, and emits
    the pinned payload shape."""
    rows = (
        proxy_pair(seed=0) + minimal_proxy(seed=0) + cross_call_pair(seed=0)
    )
    for code_hex, _creation, name in rows:
        (tmp_path / (name.replace("#", "_") + ".hex")).write_text(code_hex)
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "myth"),
            "graph",
            str(tmp_path),
            "--json",
        ],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    payload = json.loads(out.stdout)
    assert payload["schema_version"] == GRAPH_SCHEMA_VERSION
    assert sorted(payload) == [
        "arena_plan",
        "collisions",
        "contracts",
        "edges",
        "findings",
        "proxy_pairs",
        "schema_version",
        "stats",
    ]
    assert len(payload["contracts"]) == 6
    assert len(payload["edges"]) == 3
    assert all(e["resolved"] for e in payload["edges"])
    assert {e["provenance"] for e in payload["edges"]} == {
        PROV_CONSTANT,
        PROV_PROXY_SLOT,
        PROV_MINIMAL_PROXY,
    }
    assert payload["stats"]["resolve_rate"] == 1.0
    assert len(payload["proxy_pairs"]) == 2
    # sub-second per pair, by a wide margin: the whole 6-contract link
    assert payload["stats"]["wall_ms"] < 1000.0
    # the arena co-location plan maps each forwarder onto its callee
    plan = payload["arena_plan"]
    assert any(callees for callees in plan.values())


def test_myth_graph_human_output(tmp_path):
    rows = proxy_pair(seed=0)
    for code_hex, _creation, name in rows:
        (tmp_path / (name.replace("#", "_") + ".hex")).write_text(code_hex)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "myth"), "graph", str(tmp_path)],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "Link graph:" in out.stdout
    assert "proxy-slot" in out.stdout
    assert "Proxy pairs:" in out.stdout
    assert "Arena co-location plan:" in out.stdout


# -- the four link lint checks ----------------------------------------------
def test_link_checks_registered():
    assert LINK_CHECKS <= LINT_CHECKS
    assert LINT_SCHEMA_VERSION == 3
    assert len(LINT_CHECKS) == 13


def test_lint_delegatecall_to_upgradeable_target():
    proxy_hex = proxy_pair(seed=0)[0][0]
    assert "delegatecall-to-upgradeable-target" in _checks(
        summary_for(proxy_hex)
    )


def test_lint_tainted_cross_contract_call_arg():
    caller_hex = cross_call_pair(seed=0)[0][0]
    assert "tainted-cross-contract-call-arg" in _checks(
        summary_for(caller_hex)
    )
    # a minimal proxy forwards calldata BY DESIGN: never flagged
    forwarder_hex = minimal_proxy(seed=0)[0][0]
    assert "tainted-cross-contract-call-arg" not in _checks(
        summary_for(forwarder_hex)
    )


def test_lint_untrusted_return_data_in_guard():
    caller_hex = cross_call_pair(seed=0)[0][0]
    assert "untrusted-return-data-in-guard" in _checks(
        summary_for(caller_hex)
    )
    # the proxy never branches on returned memory
    assert "untrusted-return-data-in-guard" not in _checks(
        summary_for(proxy_pair(seed=0)[0][0])
    )


def test_lint_proxy_storage_collision_needs_the_pair():
    """The pair-level check fires from LinkSet.findings() with both
    row names attached — a single contract can never produce it."""
    rows = proxy_pair(seed=7, collide=True)
    assert "proxy-storage-collision" not in _checks(
        summary_for(rows[0][0])
    )
    linkset = _linkset_of(rows)
    (finding,) = [
        f
        for f in linkset.findings()
        if f["check"] == "proxy-storage-collision"
    ]
    assert finding["contract"] == rows[0][2]
    assert rows[1][2] in finding["detail"]


# -- routing schema v4 ------------------------------------------------------
def test_routing_v4_link_features_and_backcompat():
    from mythril_tpu.observe.routing import (
        SCHEMA_VERSION,
        V4_FEATURE_KEYS,
        features_for,
        parse_record,
    )

    assert SCHEMA_VERSION == 4
    rows = proxy_pair(seed=0)
    linkset = _linkset_of(rows)
    proxy_ch = next(
        ch for ch, node in linkset.nodes.items() if node.is_proxy
    )
    feats = features_for(rows[0][0], link=linkset.node_meta(proxy_ch))
    assert feats["link_is_proxy"] is True
    assert feats["link_proxy_kind"] == "eip1967"
    assert feats["link_out_degree"] == 1
    assert feats["link_resolved_degree"] == 1
    assert feats["link_delegatecall_sites"] == 1
    assert isinstance(feats["link_escape_density"], float)
    # v3 records (journey_id, no link block) None-fill the v4 columns
    v3 = {
        "schema_version": 3,
        "contract": "Old",
        "code_hash": "cd" * 32,
        "features": {"code_bytes": 4},
        "outcome": {"route": "host-walk"},
        "journey_id": "j-1",
    }
    parsed = parse_record(json.dumps(v3))
    for key in V4_FEATURE_KEYS:
        assert parsed["features"][key] is None
    assert parsed["journey_id"] == "j-1"


# -- consumers: triage + watcher + corpusgen --------------------------------
def test_chainstream_triage_carries_link_block():
    from mythril_tpu.chainstream.triage import StaticTriage

    triage = StaticTriage()
    verdict = triage.triage(bytes.fromhex(proxy_pair(seed=0)[0][0]))
    assert verdict.link is not None
    assert verdict.link["is_proxy"] is True
    assert verdict.link["proxy_kind"] == "eip1967"
    assert verdict.link["upgradeable"] is True
    assert "delegatecall-to-upgradeable-target" in verdict.findings
    assert verdict.as_dict()["link"]["delegatecall_sites"] == 1


def test_watcher_detects_constructor_wired_proxy():
    """The satellite: a deploy tx whose INIT CODE stores an address
    into the EIP-1967 impl slot surfaces BOTH the new contract and the
    baked implementation (kind proxy-deployment) — no upgradeTo call
    ever appears for these."""
    from mythril_tpu.chainstream.watcher import (
        KIND_DEPLOYMENT,
        KIND_PROXY_DEPLOYMENT,
        KIND_PROXY_UPGRADE,
        UPGRADE_SELECTOR_HEXES,
        ChainWatcher,
        _init_code_implementation,
    )

    assert UPGRADE_SELECTOR_HEXES == {"3659cfe6", "4f1ef286"}
    impl = 0xABC
    wired = (
        "0x73" + f"{impl:040x}" + "7f" + f"{EIP1967_IMPL_SLOT:064x}" + "55"
    )
    assert _init_code_implementation(wired) == f"0x{impl:040x}"
    assert _init_code_implementation("0x600160005500") is None

    class _Pool:
        def get_receipt(self, _tx_hash):
            return {"contractAddress": "0x" + "11" * 20}

    class _Stub:
        pool = _Pool()

    block = {
        "transactions": [
            {"hash": "0xdead", "to": None, "input": wired},
            {
                "to": "0x" + "22" * 20,
                "input": "0x3659cfe6" + f"{impl:064x}",
            },
        ]
    }
    targets = ChainWatcher._extract_targets(_Stub(), block)
    assert ("0x" + "11" * 20, KIND_DEPLOYMENT) in targets
    assert (f"0x{impl:040x}", KIND_PROXY_DEPLOYMENT) in targets
    # an upgrade surfaces the implementation AND the proxy (the pair)
    assert (f"0x{impl:040x}", KIND_PROXY_UPGRADE) in targets
    assert ("0x" + "22" * 20, KIND_PROXY_UPGRADE) in targets


def test_bench_corpus_carries_link_fixtures():
    corpus = synth_bench_corpus(
        32, proxy_pairs=1, minimal_proxies=1, cross_call_pairs=1
    )
    assert len(corpus) == 32
    names = [name for _code, _creation, name in corpus]
    assert any(n.startswith("proxy#") for n in names)
    assert any(n.startswith("impl#") for n in names)
    assert any(n.startswith("minproxy#") for n in names)
    assert any(n.startswith("crosscaller#") for n in names)
    # every fixture row links: the bench's resolve-rate headline is 1.0
    fixture_rows = [
        row
        for row in corpus
        if row[2].split("#")[0]
        in ("proxy", "impl", "minproxy", "mincallee", "crosscaller", "crosscallee")
    ]
    linkset = _linkset_of(fixture_rows)
    assert linkset.stats()["resolve_rate"] == 1.0


def test_linker_is_jax_free():
    """The static link plane must stay pure host work."""
    out = subprocess.run(
        [
            sys.executable,
            "-c",
            (
                "import sys;"
                "import mythril_tpu.analysis.static.callgraph;"
                "import mythril_tpu.analysis.static.linkset;"
                "assert not any(m == 'jax' or m.startswith('jax.') "
                "for m in sys.modules), 'jax leaked into the linker'"
            ),
        ],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
