"""Batched dispatcher-probe tests (coverage bitmap + surface triage)."""

from pathlib import Path

import pytest

from mythril_tpu.analysis.dispatcher_probe import probe_dispatcher

from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES as REFERENCE


def test_probe_simple_contract():
    # dispatcher for selector 0xaa000000: storage write; else revert
    shift = bytes.fromhex("600035") + bytes([0x60, 224]) + bytes.fromhex("1c")
    check = bytes.fromhex("63aa000000") + bytes.fromhex("14")
    revert_arm = bytes.fromhex("60006000fd")
    # prefix = shift + check + PUSH1 dest + JUMPI + revert
    dest = len(shift) + len(check) + 3 + len(revert_arm)
    prefix = shift + check + bytes([0x60, dest, 0x57]) + revert_arm
    code = (prefix + bytes.fromhex("5b600160005500")).hex()
    results = probe_dispatcher(code, fuzz_lanes=1)
    by_label = {r["function"]: r for r in results}
    # the recovered selector lane must succeed and write storage
    selector_lane = by_label.get("0xaa000000")
    assert selector_lane is not None
    assert selector_lane["status"] == "stopped"
    assert selector_lane["storage_writes"] == {"0x0": "0x1"}
    assert selector_lane["coverage_percent"] > 0
    # empty calldata hits the revert arm
    assert by_label["<empty calldata>"]["status"] == "reverted"


@pytest.mark.skipif(not REFERENCE.is_dir(), reason="reference testdata absent")
def test_probe_metacoin():
    code = (REFERENCE / "metacoin.sol.o").read_text().strip()
    results = probe_dispatcher(code)
    statuses = {r["function"]: r["status"] for r in results}
    # both recovered selectors execute; junk calldata reverts
    selector_lanes = [r for r in results if r["function"].startswith("0x")]
    assert len(selector_lanes) == 2
    assert all(r["status"] == "returned" for r in selector_lanes)
    assert statuses["<empty calldata>"] == "reverted"
    # selector lanes cover strictly more code than the dispatcher bail-out
    empty_cov = next(
        r["coverage_percent"] for r in results if r["function"] == "<empty calldata>"
    )
    assert all(r["coverage_percent"] > empty_cov for r in selector_lanes)
