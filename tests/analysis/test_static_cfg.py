"""Static analysis layer: golden CFGs, prune decisions, screen, and
the pruned-vs-unpruned differential (analysis/static).

Tier-1 via the `static` marker (tox -e static runs it alone).
"""

from __future__ import annotations

import pytest

from mythril_tpu.analysis.corpusgen import deadweight_contract
from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES
from mythril_tpu.analysis.static import (
    analyze_bytecode,
    screen_modules,
    summary_for,
)
from mythril_tpu.disassembler import asm
from mythril_tpu.laser.batch.seeds import dispatcher_seeds

pytestmark = pytest.mark.static


def _fixture(name: str) -> str:
    return (GOLDEN_FIXTURES / f"{name}.sol.o").read_text().strip()


# -- golden CFG + prune decisions -------------------------------------------
def test_golden_deadweight_contract():
    """The dead-revert-block shape: every static decision pinned."""
    summary = analyze_bytecode(deadweight_contract(0))
    stats = summary.stats()
    assert stats["blocks"] == 10
    assert stats["dead_blocks"] == 2  # the island after the const guard
    assert stats["selectors"] == 2
    assert stats["dead_selectors"] == 1
    assert {s.hex() for s in summary.dead_selectors} == {"deadd00d"}
    # the const-true guard kills its fall-through; the dead function's
    # dispatcher entry is pruned alongside it
    assert summary.dead_directions == {(4, False)}
    assert summary.inert_directions == {(33, True)}
    assert summary.prune_directions() == {(4, False), (33, True)}
    assert not stats["incomplete"]
    checks = {f["check"] for f in summary.findings()}
    assert {"unreachable-code", "dead-branch", "inert-function"} <= checks


def test_golden_computed_jump_dispatcher():
    """A computed jump the peephole cannot see: the target reaches the
    JUMP through a SWAP/POP shuffle and constant arithmetic — only the
    dataflow pass resolves it."""
    code = asm.assemble(
        """
        PUSH1 0x55      ; junk
        PUSH1 0x03      ; half the target
        DUP1
        ADD             ; 6
        PUSH1 0x06
        ADD             ; target = 12
        SWAP1
        POP             ; drop the junk, target on top
        JUMP            ; at pc 11
        JUMPDEST        ; 12
        STOP
        """
    )
    summary = analyze_bytecode(code)
    jump_pc = summary.cfg.blocks[0].end
    assert summary.flow.resolved_jumps == {jump_pc: 12}
    assert summary.flow.unresolved_jumps == set()
    assert summary.reachable_blocks == {0, 12}
    # the peephole alone must NOT have seen it (PUSH is not adjacent)
    assert jump_pc not in summary.cfg.peephole_targets


def test_golden_const_fold_and_dead_island():
    code = asm.assemble(
        """
        PUSH1 0x01
        PUSH1 0x08
        JUMPI           ; always taken
        PUSH1 0x00      ; dead island, not JUMPDEST-led
        STOP
        JUMPDEST        ; 0x08
        CALLER
        SUICIDE
        """
    )
    summary = analyze_bytecode(code)
    assert summary.dead_directions == {(4, False)}
    assert summary.dead_blocks == {5}
    assert summary.dead_instructions == 2
    assert "SUICIDE" in summary.features
    assert "PUSH1" in summary.features


def test_golden_underflow_and_invalid_jump():
    # ADD on an empty stack: definite underflow, flagged not pruned
    summary = analyze_bytecode(asm.assemble("ADD\nSTOP"))
    assert summary.flow.underflow_blocks == {0}
    assert {f["check"] for f in summary.findings()} == {"stack-underflow"}

    # const jump to a non-JUMPDEST: invalid, flagged not pruned (the
    # taken lane halts ERR_JUMP — a real finding, not dead code)
    summary = analyze_bytecode(
        asm.assemble("PUSH1 0x04\nJUMP\nSTOP\nSTOP")
    )
    assert summary.flow.invalid_jumps == {2: 4}
    assert not summary.dead_directions


def test_golden_fixture_suicide():
    """Real solc output: dispatcher recovered, jumps fully resolved,
    trailing dead region counted, screen keeps the killable module."""
    summary = summary_for(_fixture("suicide"))
    stats = summary.stats()
    assert stats["blocks"] == 9
    assert stats["reachable_blocks"] == 7
    assert stats["dead_blocks"] == 2
    assert stats["selectors"] == 1
    assert stats["dead_selectors"] == 0
    assert stats["unresolved_jumps"] == 0
    assert stats["resolved_jumps"] == 4
    applicable, skipped = summary.applicable_modules()
    assert "AccidentallyKillable" in applicable
    assert "EtherThief" in skipped  # no CALL anywhere in the code
    # the opcode layer keeps IntegerArithmetics (ADD is present); the
    # semantic layer proves every arith site constant and non-wrapping
    # and skips it — the fixture's golden issue set (SWC-106 only)
    # confirms the module never fired here
    opcode_applicable, _ = summary.applicable_modules(semantic=False)
    assert "IntegerArithmetics" in opcode_applicable
    assert "IntegerArithmetics" in skipped


def test_golden_fixture_overflow():
    summary = summary_for(_fixture("overflow"))
    stats = summary.stats()
    assert stats["blocks"] == 29
    assert stats["selectors"] == 4
    assert stats["dead_selectors"] == 0
    assert stats["unresolved_jumps"] == 0
    applicable, skipped = summary.applicable_modules()
    assert "IntegerArithmetics" in applicable
    assert "AccidentallyKillable" in skipped


# -- the screen -------------------------------------------------------------
def test_screen_minimal_killable():
    applicable, skipped = screen_modules(
        analyze_bytecode("33ff").features
    )
    assert applicable == ["AccidentallyKillable"]
    assert len(skipped) == 13


def test_screen_conjunction():
    # CALL present but no state op: StateChangeAfterCall screens off
    # while the other call modules stay
    features = {"CALL", "STOP", "PUSH1"}
    applicable, skipped = screen_modules(features)
    assert "StateChangeAfterCall" in skipped
    assert "ExternalCalls" in applicable
    assert "UncheckedRetval" in applicable
    features.add("SSTORE")
    applicable, _ = screen_modules(features)
    assert "StateChangeAfterCall" in applicable


def test_unknown_module_is_never_screened():
    applicable, skipped = screen_modules(set(), ["SomeCustomDetector"])
    assert applicable == ["SomeCustomDetector"] and not skipped


# -- the prune feed ---------------------------------------------------------
def test_dispatcher_seeds_drop_dead_selector_and_count():
    code = deadweight_contract(0)
    summary = analyze_bytecode(code)
    unpruned = dispatcher_seeds(code, 68)
    pruned = dispatcher_seeds(code, 68, prune=summary)
    assert len(unpruned) - len(pruned) == 2  # zero-args + max-args seed
    assert summary.seeds_dropped == 2
    dead = bytes.fromhex("deadd00d")
    assert all(not s.startswith(dead) for s in pruned)
    live = next(s for s in summary.dispatcher if s.selector != dead)
    assert any(seed.startswith(live.selector) for seed in pruned)


def test_prune_log_is_debug_visible(caplog):
    import logging

    code = deadweight_contract(0)
    summary = analyze_bytecode(code)
    with caplog.at_level(logging.DEBUG, logger="mythril_tpu.laser.batch.seeds"):
        dispatcher_seeds(code, 68, prune=summary)
    assert any("static prune dropped" in r.message for r in caplog.records)
    assert any("deadd00d" in r.message for r in caplog.records)


def test_explorer_attaches_feed_and_masks_flips():
    """The explorer wires the feed at construction: dead directions
    populate the per-track mask, the seed plan drops the inert
    selector, and the counters say so."""
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

    explorer = DeviceCorpusExplorer([deadweight_contract(0)], waves=1)
    track = explorer.tracks[0]
    assert track.static is not None
    assert track.static_dead == {(4, False), (33, True)}
    assert explorer.stats.static_summaries == 1
    inputs = explorer._seed_phase_inputs()
    assert explorer.stats.static_seeds_dropped == 2
    dead = bytes.fromhex("deadd00d")
    assert all(
        not data.startswith(dead) for _, data in inputs[0]
    )


def test_explorer_feed_disabled_by_flag():
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer
    from mythril_tpu.support.support_args import args

    args.static_prune = False
    try:
        explorer = DeviceCorpusExplorer([deadweight_contract(0)], waves=1)
        assert explorer.tracks[0].static is None
        assert explorer.tracks[0].static_dead == frozenset()
    finally:
        args.static_prune = True


# -- the cache --------------------------------------------------------------
def test_summary_cache_by_code_hash():
    from mythril_tpu.analysis.static import static_cache_stats

    code = deadweight_contract(1)
    first = summary_for(code)
    again = summary_for("0x" + code)  # prefix-insensitive key
    assert first is again
    stats = static_cache_stats()
    assert stats["hits"] >= 1


# -- the differential (acceptance criterion) --------------------------------
def _fingerprints(results):
    return {
        (r["name"], i["swc-id"], i["address"])
        for r in results
        for i in r["issues"]
    }


@pytest.mark.parametrize("static_prune", [True, False])
def test_differential_prepares(static_prune):
    """Smoke both legs build summaries/skip them without error."""
    from mythril_tpu.support.support_args import args

    previous = args.static_prune
    args.static_prune = static_prune
    try:
        from mythril_tpu.analysis.static import static_prune_enabled

        assert static_prune_enabled() == static_prune
    finally:
        args.static_prune = previous


def test_differential_issue_sets_match():
    """Pruned and unpruned analysis must report the SAME issue set on
    the fault-suite contracts (KILLABLE/WRITER) plus the deadweight
    shape whose whole point is to be heavily pruned."""
    from mythril_tpu.analysis.corpus import analyze_corpus
    from mythril_tpu.support.support_args import args

    contracts = [
        ("33ff", "", "Killable"),  # the fault suite's KILLABLE
        ("6001600055600060015500", "", "Writer"),  # the WRITER fixture
        (deadweight_contract(0), "", "Deadweight"),
    ]

    def leg(static_prune: bool):
        previous = args.static_prune
        args.static_prune = static_prune
        try:
            return analyze_corpus(
                contracts,
                transaction_count=1,
                execution_timeout=8,
                processes=1,
                use_device=False,
            )
        finally:
            args.static_prune = previous

    pruned = leg(True)
    unpruned = leg(False)
    assert all(r["error"] is None for r in pruned + unpruned)
    assert _fingerprints(pruned) == _fingerprints(unpruned)
    # and the runs actually found things (the differential is not
    # trivially empty): the killable + the deadweight's SWC-110
    assert any(swc == "106" for _, swc, _ in _fingerprints(pruned))
    assert any(swc == "110" for _, swc, _ in _fingerprints(pruned))
