"""Taint & value-set static layer: propagation goldens, the semantic
detector screen's soundness sweep, the static-answer triage tier, the
taint lint checks, and the routing-schema back-compat.

Tier-1 via the `taint` marker (tox -e taint runs it alone).
"""

from __future__ import annotations

import json

import pytest

from mythril_tpu.analysis.corpusgen import (
    clean_contract,
    deadweight_contract,
)
from mythril_tpu.analysis.static import (
    LINT_CHECKS,
    LINT_SCHEMA_VERSION,
    TAINT_ATTACKER,
    analyze_bytecode,
    screen_modules,
    summary_for,
)
from mythril_tpu.analysis.static.vsa import ATTACKER_ADDRESS
from mythril_tpu.support.support_args import args as support_args

from tests.analysis.test_module_positive_fixtures import FIXTURES

pytestmark = pytest.mark.taint


def _fixture(name: str) -> str:
    from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES

    return (GOLDEN_FIXTURES / f"{name}.sol.o").read_text().strip()


def _checks(summary):
    return {f["check"] for f in summary.findings()}


# -- taint propagation goldens ----------------------------------------------
def test_calldata_taints_jump_target():
    # CALLDATALOAD(0); JUMP; JUMPDEST; STOP
    summary = analyze_bytecode("600035565b00")
    taint = summary.taint
    assert not taint.incomplete
    assert taint.jump_targets == {3: (None, TAINT_ATTACKER)}
    assert taint.tainted_jump_pcs() == [3]
    assert "tainted-jump-target" in _checks(summary)


def test_caller_taints_delegatecall_target():
    # PUSH1 0 x4; CALLDATALOAD(0); PUSH2 gas; DELEGATECALL; POP; STOP
    summary = analyze_bytecode(
        "6000600060006000" + "600035" + "61ffff" + "f45000"
    )
    taint = summary.taint
    (site,) = taint.call_sites.values()
    assert site["kind"] == "DELEGATECALL"
    assert site["target"][1] & TAINT_ATTACKER
    assert site["value"] is None  # DELEGATECALL carries no value
    assert "tainted-delegatecall-target" in _checks(summary)


def test_mload_after_tainted_mstore_joins():
    # MSTORE(0, CALLDATALOAD(0)); JUMP(MLOAD(0)) — the taint must
    # survive the memory round-trip even though the constant does not
    summary = analyze_bytecode("600035600052600051565b00")
    taint = summary.taint
    jump_pc = max(taint.jump_targets)
    assert taint.jump_targets[jump_pc][0] is None
    assert taint.jump_targets[jump_pc][1] & TAINT_ATTACKER


def test_sload_of_tainted_written_slot_joins():
    # SSTORE(0, CALLDATALOAD(0)); SSTORE(1, SLOAD(0)) — the second
    # store's VALUE carries the attacker bit through storage
    summary = analyze_bytecode("600035600055600054600155" + "00")
    taint = summary.taint
    values = sorted(taint.sstore_values.items())
    assert values[0][1][1] & TAINT_ATTACKER  # the direct store
    assert values[1][1][1] & TAINT_ATTACKER  # through the slot
    # slots themselves are constants: the arbitrary-write screen holds
    assert all(v[0] is not None for v in taint.sstore_slots.values())


def test_origin_reaches_condition():
    # ORIGIN; CALLER; EQ; PUSH1 7; JUMPI; STOP; JUMPDEST; STOP
    summary = analyze_bytecode("3233146007" + "57005b00")
    taint = summary.taint
    assert taint.origin_condition_pcs == [5]
    assert taint.caller_condition_pcs == [5]
    assert taint.origin_compare_pcs == [2]
    assert "tx-origin-as-auth" in _checks(summary)
    # guarded: a CALLER/ORIGIN comparison exists, so a selfdestruct
    # behind it would NOT be flagged unprotected
    assert "unprotected-selfdestruct" not in _checks(summary)


def test_unprotected_selfdestruct_flagged():
    summary = analyze_bytecode("33ff")  # CALLER; SUICIDE — no guard
    assert "unprotected-selfdestruct" in _checks(summary)
    assert 1 in summary.taint.selfdestruct_sites


def test_constant_facts_resolved():
    """The value-set half: constant call targets and storage slots."""
    # CALL(gas=0xffff, to=0x1234, value=0, ...); SSTORE(5, 1); STOP
    summary = analyze_bytecode(
        "6000600060006000" + "6000" + "611234" + "61ffff" + "f150"
        + "6001600555" + "00"
    )
    assert list(summary.vsa.resolved_call_targets.values()) == [0x1234]
    assert summary.vsa.constant_storage_writes == {5}
    stats = summary.stats()
    assert stats["resolved_call_target_count"] == 1
    assert stats["constant_storage_slots"] == ["0x5"]


def test_function_fingerprints_stable_and_content_sensitive():
    code_a = clean_contract(0)
    summary_a = analyze_bytecode(code_a)
    assert len(summary_a.function_fingerprints) == 2
    # deterministic across rebuilds
    assert (
        analyze_bytecode(code_a).function_fingerprints
        == summary_a.function_fingerprints
    )
    # a different body (seed bumps the stored constant) changes the
    # touched function's fingerprint
    summary_b = analyze_bytecode(clean_contract(1))
    fp_a = set(summary_a.function_fingerprints.values())
    fp_b = set(summary_b.function_fingerprints.values())
    assert fp_a != fp_b


# -- the semantic screen ----------------------------------------------------
@pytest.mark.parametrize("module", sorted(FIXTURES))
def test_screen_soundness_sweep(module):
    """THE soundness pin: the semantic screen must never skip the
    module that fires on its own positive fixture."""
    code, _swc = FIXTURES[module]
    summary = analyze_bytecode(code)
    applicable, _skipped = summary.applicable_modules()
    assert module in applicable, (
        f"semantic screen skipped {module} on its own positive fixture"
    )


def test_semantic_screen_only_narrows():
    """Layering: semantic ⊆ opcode for every fixture — the predicate
    layer can only remove mounts, never add them."""
    for module, (code, _swc) in FIXTURES.items():
        summary = analyze_bytecode(code)
        semantic, _ = summary.applicable_modules()
        opcode, _ = summary.applicable_modules(semantic=False)
        assert set(semantic) <= set(opcode), module


def test_user_assertions_screen_differential_on_exceptions():
    """The satellite fix for the dead MSTORE screen: on the real
    `exceptions` fixture (MSTORE-heavy, no AssertionFailed LOG1, no
    marker word) the opcode screen mounts UserAssertions and the
    semantic screen does not — and the golden issue set (four
    Exception State findings, all from the Exceptions module) proves
    the skip changes nothing."""
    summary = summary_for(_fixture("exceptions"))
    opcode_applicable, _ = summary.applicable_modules(semantic=False)
    semantic_applicable, _ = summary.applicable_modules()
    assert "UserAssertions" in opcode_applicable
    assert "UserAssertions" not in semantic_applicable
    # the module the fixture's findings DO come from stays mounted
    assert "Exceptions" in semantic_applicable


def test_user_assertions_end_to_end_differential_on_exceptions():
    """End-to-end half of the differential: analyzing the exceptions
    fixture with ONLY UserAssertions requested yields the same (empty)
    issue set whether the semantic screen skips the module (prune on)
    or the full mount runs it (prune off)."""
    from mythril_tpu.analysis.corpus import analyze_corpus

    contracts = [(_fixture("exceptions"), "", "Exceptions")]

    def leg(static_prune: bool):
        previous = support_args.static_prune
        support_args.static_prune = static_prune
        try:
            return analyze_corpus(
                contracts,
                transaction_count=1,
                execution_timeout=8,
                processes=1,
                use_device=False,
                modules=["UserAssertions"],
            )
        finally:
            support_args.static_prune = previous

    screened = leg(True)
    unscreened = leg(False)
    assert all(r["error"] is None for r in screened + unscreened)
    assert _fingerprints(screened) == _fingerprints(unscreened) == set()


def test_user_assertions_mounts_on_log_topic_and_marker():
    # its positive fixture: PUSH32 topic; LOG1
    log_code, _ = FIXTURES["UserAssertions"]
    applicable, _ = analyze_bytecode(log_code).applicable_modules()
    assert "UserAssertions" in applicable
    # the MythX marker word anywhere in the code keeps the module too
    marker_code = "7f" + "cafe" * 15 + "0000" + "600052" + "00"
    applicable, _ = analyze_bytecode(marker_code).applicable_modules()
    assert "UserAssertions" in applicable


def test_screen_attacker_address_constant_still_mounts():
    """A CONSTANT delegatecall target equal to the attacker actor
    still satisfies `target == ACTORS.attacker` — must mount."""
    push_attacker = "73" + f"{ATTACKER_ADDRESS:040x}"
    code = "6000600060006000" + push_attacker + "61ffff" + "f45000"
    applicable, _ = analyze_bytecode(code).applicable_modules()
    assert "ArbitraryDelegateCall" in applicable


def test_screen_falls_back_on_incomplete_taint():
    summary = analyze_bytecode(clean_contract(0))
    assert summary.static_answerable
    summary.taint.incomplete = True  # simulate a bail
    applicable, _ = summary.applicable_modules()
    opcode_applicable, _ = summary.applicable_modules(semantic=False)
    assert applicable == opcode_applicable  # opcode screen decides
    assert not summary.static_answerable


def test_screen_modules_without_taint_is_opcode_only():
    applicable, skipped = screen_modules({"SSTORE", "PUSH1", "STOP"})
    assert "ArbitraryStorage" in applicable


# -- the static-answer triage tier ------------------------------------------
def test_clean_contract_is_answerable_and_deadweight_is_not():
    assert analyze_bytecode(clean_contract(0)).static_answerable
    # deadweight keeps a real SWC-110 (guarded INVALID): never triaged
    assert not analyze_bytecode(deadweight_contract(0)).static_answerable


def test_lint_dict_schema_version_and_check_registry():
    row = analyze_bytecode(clean_contract(0)).lint_dict(name="clean")
    assert row["schema_version"] == LINT_SCHEMA_VERSION
    assert row["static_answerable"] is True
    assert row["fingerprint_count"] == 2
    # every emitted check is registered (the --fail-on validator)
    for code in ("33ff", "600035565b00", deadweight_contract(0)):
        for finding in analyze_bytecode(code).findings():
            assert finding["check"] in LINT_CHECKS


def _fingerprints(results):
    return {
        (r["name"], i["swc-id"], i["address"])
        for r in results
        for i in r["issues"]
    }


def test_corpus_triage_differential():
    """analyze_corpus with the triage tier on: the clean contract is
    answered statically (empty issues, no walk), everything else
    walks — and the ISSUE SET matches the tier-off run exactly."""
    from mythril_tpu.analysis.corpus import analyze_corpus

    contracts = [
        (clean_contract(0), "", "Clean"),
        ("33ff", "", "Killable"),
    ]

    def leg(static_answer: bool):
        previous = support_args.static_answer
        support_args.static_answer = static_answer
        try:
            return analyze_corpus(
                contracts,
                transaction_count=1,
                execution_timeout=8,
                processes=1,
                use_device=False,
            )
        finally:
            support_args.static_answer = previous

    triaged = leg(True)
    walked = leg(False)
    assert all(r["error"] is None for r in triaged + walked)
    assert _fingerprints(triaged) == _fingerprints(walked)
    clean_result = next(r for r in triaged if r["name"] == "Clean")
    assert clean_result["static_answered"] is True
    assert clean_result["issues"] == []
    assert clean_result["states"] == 0  # no walk happened
    assert clean_result["complete"] is True
    # the killable contract went through the full path and found SWC-106
    assert any(swc == "106" for _, swc, _ in _fingerprints(triaged))
    # the tier-off leg actually walked the clean contract
    walked_clean = next(r for r in walked if r["name"] == "Clean")
    assert not walked_clean.get("static_answered")


def test_triage_respects_no_static_prune():
    """--no-static-prune restores full-mount parity: with the prune
    layer off the triage tier must never fire even when
    args.static_answer is on."""
    from mythril_tpu.analysis.static import static_answer_enabled

    prev_answer = support_args.static_answer
    prev_prune = support_args.static_prune
    support_args.static_answer = True
    try:
        support_args.static_prune = False
        assert not static_answer_enabled()
        support_args.static_prune = True
        assert static_answer_enabled()
    finally:
        support_args.static_answer = prev_answer
        support_args.static_prune = prev_prune


def test_explorer_counts_answerable_tracks():
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

    explorer = DeviceCorpusExplorer(
        [clean_contract(0), deadweight_contract(0)], waves=1
    )
    assert explorer.stats.static_summaries == 2
    assert explorer.stats.static_answered == 1


# -- routing schema v2 ------------------------------------------------------
def test_routing_features_carry_taint_block():
    from mythril_tpu.observe.routing import features_for

    feats = features_for(clean_contract(0))
    assert feats["static_answerable"] is True
    # the dispatcher's selector compares are calldata-tainted JUMPI
    # guards, so density is nonzero even on the clean shape — what
    # makes it CLEAN is that no sink predicate holds, not zero taint
    assert 0.0 < feats["taint_density"] < 1.0
    assert feats["fingerprints"] == 2
    assert feats["resolved_call_targets"] == 0


def test_routing_v1_records_parse_in_tail_reader(tmp_path):
    """The back-compat pin: a v1 JSONL line (no taint features, no
    journey_id) parses through the tail reader and comes back
    normalized to the current column set (v3: + journey_id; v4: +
    link features — their None-fill is pinned in
    tests/analysis/test_callgraph.py)."""
    from mythril_tpu.observe.routing import (
        SCHEMA_VERSION,
        V2_FEATURE_KEYS,
        parse_record,
        read_records,
    )

    assert SCHEMA_VERSION == 4
    v1 = {
        "schema_version": 1,
        "contract": "Legacy",
        "code_hash": "ab" * 32,
        "features": {
            "code_bytes": 11,
            "storage_op_density": 0.1,
            "call_op_density": 0.0,
        },
        "outcome": {"route": "host-walk", "issues": 0},
    }
    v2 = dict(v1, schema_version=2, contract="Fresh")
    v2["features"] = dict(
        v1["features"], taint_density=0.5, static_answerable=False,
        tainted_sinks=3, resolved_call_targets=1, fingerprints=2,
    )
    path = tmp_path / "routing_features.jsonl"
    path.write_text(
        json.dumps(v1) + "\n" + json.dumps(v2) + "\n" + "{broken\n"
    )
    records = read_records(str(path))
    assert [r["contract"] for r in records] == ["Legacy", "Fresh"]
    legacy = records[0]
    for key in V2_FEATURE_KEYS:
        assert key in legacy["features"]
    assert legacy["features"]["taint_density"] is None
    assert records[1]["features"]["taint_density"] == 0.5
    # v3 normalization: pre-journey records read journey_id None
    assert legacy["journey_id"] is None
    # a FUTURE schema refuses instead of mis-parsing
    with pytest.raises(ValueError):
        parse_record(json.dumps(dict(v1, schema_version=99)))


def test_routing_route_classification_static_answer():
    from mythril_tpu.observe.routing import outcome_for

    assert (
        outcome_for({"static_answered": True})["route"] == "static-answer"
    )
