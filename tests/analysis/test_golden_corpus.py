"""Golden end-to-end runs on the reference's precompiled contracts
(reference test strategy: tests/cmd_line_test.py +
testdata/outputs_expected golden files)."""

import os
from pathlib import Path

import pytest

from mythril_tpu.analysis.security import fire_lasers
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.ethereum.evmcontract import EVMContract

from mythril_tpu.analysis.goldens import GOLDEN_FIXTURES as INPUTS

# EXPECTED must follow the same override-first rule as INPUTS
# (goldens._fixture_dir): a MYTHRIL_REFERENCE_DIR override redirects
# BOTH, or the easm comparison would diff the override's bytecode
# against the vendored snapshot's goldens.
_VENDORED_EASM = (
    Path(__file__).parents[1] / "testdata" / "vendored" / "outputs_expected_easm"
)
if os.environ.get("MYTHRIL_REFERENCE_DIR"):
    EXPECTED = (
        Path(os.environ["MYTHRIL_REFERENCE_DIR"])
        / "tests"
        / "testdata"
        / "outputs_expected"
    )
elif _VENDORED_EASM.is_dir():
    EXPECTED = _VENDORED_EASM
else:
    EXPECTED = Path("/root/reference/tests/testdata/outputs_expected")

if not INPUTS.is_dir():  # pragma: no cover
    pytest.skip(
        "fixture bytecode not found (vendored copy missing and no "
        "reference checkout); set MYTHRIL_REFERENCE_DIR",
        allow_module_level=True,
    )


def analyze(name, tx_count=2, timeout=60):
    code = (INPUTS / name).read_text().strip()
    contract = EVMContract(code=code, name=name)
    sym = SymExecWrapper(
        contract,
        address=0x901D573B8CE8C997DE5F19173C32D966B4FA55FE,
        strategy="bfs",
        execution_timeout=timeout,
        create_timeout=10,
        transaction_count=tx_count,
        compulsory_statespace=False,
    )
    return {i.swc_id for i in fire_lasers(sym)}


def test_easm_golden_all_inputs():
    """Disassembly must match the reference's golden .easm files
    byte-for-byte."""
    count = 0
    for f in sorted(INPUTS.glob("*.sol.o")):
        contract = EVMContract(code=f.read_text().strip(), name=f.name)
        gold = (EXPECTED / (f.name + ".easm")).read_text()
        assert contract.get_easm() == gold, f.name
        count += 1
    assert count == 13


def test_suicide_contract():
    assert "106" in analyze("suicide.sol.o")


def test_origin_contract():
    assert "115" in analyze("origin.sol.o")


def test_exceptions_contract():
    assert "110" in analyze("exceptions.sol.o")


def test_multi_contracts():
    assert "105" in analyze("multi_contracts.sol.o")


def test_nonascii_contract_clean():
    assert analyze("nonascii.sol.o") == set()


@pytest.mark.slow
def test_overflow_contract():
    assert "101" in analyze("overflow.sol.o", timeout=90)


@pytest.mark.slow
def test_underflow_contract():
    assert "101" in analyze("underflow.sol.o", timeout=90)


@pytest.mark.slow
def test_ether_send_contract():
    swcs = analyze("ether_send.sol.o", timeout=90)
    assert "105" in swcs


@pytest.mark.slow
def test_kinds_of_calls_contract():
    swcs = analyze("kinds_of_calls.sol.o", timeout=90)
    assert "112" in swcs
    assert "104" in swcs


@pytest.mark.slow
def test_returnvalue_contract():
    assert "104" in analyze("returnvalue.sol.o", timeout=90)
