"""Unit tests for the prepass witness -> Issue conversion
(analysis/prepass.py) and the phase profiler."""

from mythril_tpu.analysis.prepass import (
    REPLAY_GAS_LIMIT,
    witness_issues,
)
from mythril_tpu.ethereum.evmcontract import EVMContract
from mythril_tpu.support.phase_profile import PhaseProfile

# PUSH1 0; CALLDATALOAD; PUSH1 7; JUMPI; STOP; JUMPDEST; ASSERT_FAIL
ASSERTING = "600035600757005bfe"


def _outcome(**record):
    base = {"pc": 8, "input": "42" * 36, "gas_min": 100, "gas_max": 200}
    base.update(record)
    return {"triggers": {"assert-violation": [base]}, "stats": {}}


def test_assert_witness_becomes_swc110_issue():
    contract = EVMContract(ASSERTING, name="A")
    issues = witness_issues(contract, _outcome(), 0xA11CE)
    assert len(issues) == 1
    issue = issues[0]
    assert (issue.swc_id, issue.address, issue.severity) == ("110", 8, "Medium")
    assert issue.provenance == "device-prepass"
    assert issue.min_gas_used == 100 and issue.max_gas_used == 200
    step = issue.transaction_sequence["steps"][0]
    assert step["input"] == "0x" + "42" * 36
    assert step["address"] == hex(0xA11CE)


def test_witness_not_at_assert_byte_is_rejected():
    contract = EVMContract(ASSERTING, name="A")
    # pc 6 is STOP territory, not the designated INVALID byte
    assert witness_issues(contract, _outcome(pc=6), 0xA11CE) == []


def test_witness_beyond_replay_gas_limit_is_rejected():
    contract = EVMContract(ASSERTING, name="A")
    outcome = _outcome(gas_min=REPLAY_GAS_LIMIT + 1)
    assert witness_issues(contract, outcome, 0xA11CE) == []


def test_multi_step_prefix_renders_in_order():
    contract = EVMContract(ASSERTING, name="A")
    outcome = _outcome(prefix=["01" * 36])
    issues = witness_issues(contract, outcome, 0xA11CE)
    steps = issues[0].transaction_sequence["steps"]
    assert [s["input"][:4] for s in steps] == ["0x01", "0x42"]


def test_phase_profile_accumulates_and_resets():
    profile = PhaseProfile()
    profile.reset()
    with profile.measure("step"):
        pass
    with profile.measure("step"):
        pass
    profile.add("prepass", 1.5)
    snap = profile.as_dict()
    assert snap["step"]["count"] == 2
    assert snap["prepass"]["wall_s"] == 1.5
    assert "step" in str(profile)
    profile.reset()
    assert profile.as_dict() == {}


def test_whitelist_filters_device_issues_per_finding_class():
    """fire_lasers keeps a device witness only when the module it
    stands in for is whitelisted (SWC-110 <-> Exceptions,
    SWC-106 <-> AccidentallyKillable)."""
    from mythril_tpu.analysis.security import fire_lasers

    contract = EVMContract(ASSERTING, name="A")
    swc110 = witness_issues(contract, _outcome(), 0xA11CE)

    class FakeSpace:
        device_issues = swc110

    kept = fire_lasers(FakeSpace(), white_list=["Exceptions"])
    assert [i.swc_id for i in kept] == ["110"]
    dropped = fire_lasers(FakeSpace(), white_list=["AccidentallyKillable"])
    assert dropped == []


def test_device_already_proved_is_code_scoped():
    """The proven-set never collides across bytecodes: a witness in
    the analyzed runtime must not suppress findings at the same pc of
    other code (creation bytecode, dynloaded foreign contracts)."""
    from mythril_tpu.analysis.prepass import (
        device_already_proved,
        register_proven,
        reset_proven,
    )

    contract = EVMContract(ASSERTING, name="A")
    issues = witness_issues(contract, _outcome(), 0xA11CE)

    class FakeCode:
        def __init__(self, bytecode):
            self.bytecode = bytecode

    class FakeEnv:
        def __init__(self, bytecode):
            self.code = FakeCode(bytecode)

    class FakeState:
        def __init__(self, bytecode, address):
            self.environment = FakeEnv(bytecode)
            self._address = address

        def get_current_instruction(self):
            return {"address": self._address}

    reset_proven()
    try:
        register_proven(issues, ASSERTING)
        assert device_already_proved(FakeState(ASSERTING, 8), "110")
        assert not device_already_proved(FakeState("6001600101", 8), "110")
        assert not device_already_proved(FakeState(ASSERTING, 7), "110")
    finally:
        reset_proven()  # never leak proven entries into later tests
