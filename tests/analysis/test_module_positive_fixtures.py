"""Per-module positive detection fixtures: EVERY detection module in
analysis/module/modules/ has one minimal hand-assembled contract that
makes it report at least one issue end-to-end.

The structural guarantee this buys: "module silently never fires" —
the failure mode where a detector exists, loads, hooks, and then never
produces an issue on anything (the 4-round SWC-116 hole) — breaks a
test the moment it regresses, instead of surviving until someone
happens to read a golden report diff. The registry sweep at the bottom
pins that every module in the package HAS a fixture here, so adding a
module without a positive fixture fails too."""

import pytest

from mythril_tpu.analysis.security import fire_lasers
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.disassembler.disassembly import Disassembly


class FakeContract:
    def __init__(self, code, name="Test"):
        self.name = name
        self.disassembly = Disassembly(code)
        self.creation_code = None
        self.code = code


def analyze(code, tx_count=1, modules=None):
    contract = FakeContract(code)
    sym = SymExecWrapper(
        contract,
        address=0x1234,
        strategy="bfs",
        execution_timeout=90,
        create_timeout=30,
        transaction_count=tx_count,
        modules=modules,
    )
    return fire_lasers(sym, white_list=modules)


#: one forwarded-gas CALL to the caller:
#: PUSH1 0 (outsz, outoff, insz, inoff, value) CALLER PUSH2 0xffff CALL POP
_CALL_CALLER = "600060006000600060003361ffff" + "f1" + "50"
#: same call shape with a calldata-supplied target
_CALL_USER = "6000600060006000" + "6000" + "600035" + "61ffff" + "f1" + "50"

#: the AssertionFailed(string) event topic user_assertions keys on
_ASSERT_TOPIC = (
    "b42604cb105a16c8f6db8a41e6b00c0c1b4826465e8bc504b3eb3e88b3e6a4a0"
)

#: module class name -> (bytecode, expected swc ids — None skips the
#: swc check where the module reports composite/variable ids)
FIXTURES = {
    # CALLER; SELFDESTRUCT
    "AccidentallyKillable": ("33ff", {"106"}),
    # DELEGATECALL to a calldata-loaded address
    "ArbitraryDelegateCall": (
        "6000600060006000" + "600035" + "61ffff" + "f45000",
        {"112"},
    ),
    # JUMP to a calldata-loaded destination (JUMPDEST at 4 keeps one
    # branch alive; the symbolic destination is the finding)
    "ArbitraryJump": ("600035565b00", None),
    # SSTORE(key=CALLDATALOAD(0), value=1)
    "ArbitraryStorage": ("60016000355500", {"124"}),
    # send the whole balance to the caller
    "EtherThief": ("6000600060006000473361fffff15000", {"105"}),
    # calldata-gated INVALID
    "Exceptions": ("600035600757005bfe", {"110"}),
    # forwarded-gas CALL to a user-supplied address
    "ExternalCalls": (_CALL_USER + "00", {"107"}),
    # CALLDATALOAD(0) * 2 stored: the overflow witness
    "IntegerArithmetics": ("600035600202" + "60005500", {"101"}),
    # two sends in one transaction
    "MultipleSends": (_CALL_CALLER * 2 + "00", {"113"}),
    # TIMESTAMP decides a branch
    "PredictableVariables": ("42600557005b00", {"116"}),
    # SSTORE after a forwarded-gas call
    "StateChangeAfterCall": (_CALL_USER + "6001600055" + "00", {"107"}),
    # branch on ORIGIN == CALLER
    "TxOrigin": ("3233146007" + "57005b00", {"115"}),
    # CALL retval popped, never checked
    "UncheckedRetval": (_CALL_CALLER + "00", {"104"}),
    # LOG1 with the AssertionFailed(string) topic
    "UserAssertions": (
        "7f" + _ASSERT_TOPIC + "60006000" + "a1" + "00",
        {"110"},
    ),
}


@pytest.mark.parametrize("module", sorted(FIXTURES))
def test_module_fires_on_its_fixture(module):
    code, expected_swc = FIXTURES[module]
    issues = analyze(code, modules=[module])
    assert issues, f"{module} produced no issues on its positive fixture"
    if expected_swc is not None:
        found = {i.swc_id for i in issues}
        assert found & expected_swc, (
            f"{module} reported {found}, fixture expects {expected_swc}"
        )


def test_every_registered_module_has_a_fixture():
    """The sweep that keeps this file honest: a new detection module
    must land with a positive fixture."""
    from mythril_tpu.analysis.module import ModuleLoader

    registered = {
        type(m).__name__ for m in ModuleLoader().get_detection_modules()
    }
    missing = registered - set(FIXTURES)
    assert not missing, (
        f"detection modules without a positive fixture: {sorted(missing)}"
    )
