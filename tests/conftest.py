"""Test configuration.

All tests run on a virtual 8-device CPU mesh so multi-chip sharding
logic (mythril_tpu.parallel) is exercised without TPU hardware, per the
reference's "test chain interaction without a chain" strategy
(reference: tests/__init__.py + mocked RPC in tests/mythril/).

NOTE: this machine pins JAX_PLATFORMS=axon through a sitecustomize that
overrides environment variables, so the platform switch must go through
jax.config (env vars are silently ignored). XLA_FLAGS still must be set
before first backend init.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent XLA compile cache: amortize keccak/divmod compiles across runs
os.makedirs("/tmp/mtpu_xla_cache", exist_ok=True)
jax.config.update("jax_compilation_cache_dir", "/tmp/mtpu_xla_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# Kernel specialization is OFF by default under the test harness: the
# product default is on, but every distinct (specialization bucket x
# arena shape) pays a fresh XLA compile, and the many small contract
# combinations across the suite would not fit tier-1's 10-minute
# window on a 1-core host. The dedicated suite
# (tests/laser/test_specialize.py, `-m specialize`) re-enables it and
# pins the specialized-vs-generic differentials.
from mythril_tpu.support.support_args import args as _support_args  # noqa: E402

_support_args.specialize = False

# The block-level JIT rides the specialize flag (no specialized
# kernel, no block substeps) but is ALSO off explicitly: the blockjit
# suite (tests/laser/test_blockjit.py, `-m blockjit`) re-enables both
# and pins the blockjit-vs-generic differentials; product/bench
# default is on.
_support_args.blockjit = False

# The device-first solver funnel is likewise OFF by default under the
# test harness: the product default is on, but the batched diversified
# SLS dispatch pays a fresh XLA compile per stacked shape bucket, and
# running it for EVERY wave's flip frontier across the whole suite
# would not fit tier-1's window on 1 CPU core. The dedicated suite
# (tests/laser/test_solverperf.py, `-m solverperf`) re-enables it and
# pins the inverted-vs-legacy funnel differentials.
_support_args.device_first = False

# The static-answer TRIAGE TIER is OFF by default under the test
# harness (the product default is on): many suites pin wave/walk
# mechanics on tiny synthetic contracts that are provably clean, and
# triage would answer those jobs before the machinery under test ever
# runs. The semantic detector SCREEN itself stays ON (it rides
# static_prune) — its soundness is pinned by the module positive
# fixtures across the whole suite. The dedicated taint suite
# (tests/analysis/test_static_taint.py, `-m taint`) and the service
# triage test re-enable the tier and pin its behavior.
_support_args.static_answer = False


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running golden analyses (run explicitly with -m slow)"
    )
    config.addinivalue_line(
        "markers",
        "faults: deterministic fault-injection suite (resilience harness; "
        "fast — runs in tier-1, selectable with -m faults)",
    )
    config.addinivalue_line(
        "markers",
        "service: persistent analysis service suite (myth serve; CPU-only, "
        "fast — runs in tier-1, selectable with -m service)",
    )
    config.addinivalue_line(
        "markers",
        "static: static bytecode analysis suite (analysis/static: CFG "
        "recovery, dataflow, prune feed, detector screen; host-only, "
        "fast — runs in tier-1, selectable with -m static)",
    )
    config.addinivalue_line(
        "markers",
        "pipeline: pipelined wave engine suite (double-buffered async "
        "dispatch, device-side evidence compaction, donated arena "
        "reseed; CPU-only, fast — runs in tier-1, selectable with "
        "-m pipeline)",
    )
    config.addinivalue_line(
        "markers",
        "multichip: multi-chip mesh suite (sharded step, corpus "
        "scheduler with work stealing, per-group failure domains, "
        "mesh service) on the 8 simulated host devices this conftest "
        "forces — runs in tier-1, selectable with -m multichip",
    )
    config.addinivalue_line(
        "markers",
        "specialize: kernel-specialization suite (per-contract step "
        "kernels: phase pruning, superblock fusion, compile cache, "
        "CodeCache kernel eviction; CPU-only — runs in tier-1, "
        "selectable with -m specialize)",
    )
    config.addinivalue_line(
        "markers",
        "blockjit: block-level JIT suite (laser/batch/blockjit.py: "
        "block-summary goldens, block-program tables, blockjit-vs-"
        "generic differentials, mid-block OOG replay, kernel-cache "
        "block-key pin/evict, --no-blockjit parity; CPU-only — runs "
        "in tier-1, selectable with -m blockjit)",
    )
    config.addinivalue_line(
        "markers",
        "observe: unified telemetry suite (mythril_tpu/observe: "
        "metrics registry + Prometheus exposition, structured spans + "
        "Perfetto export + flight recorder, solver attribution, "
        "routing feature log, stats-merge policy; CPU-only — runs in "
        "tier-1, selectable with -m observe)",
    )
    config.addinivalue_line(
        "markers",
        "solverlab: solver query flight recorder + replay lab suite "
        "(observe/querylog capture artifacts + loss-reason taxonomy, "
        "solver funnel classification, myth solverlab replay "
        "agreement; CPU-only — runs in tier-1, selectable with "
        "-m solverlab)",
    )
    config.addinivalue_line(
        "markers",
        "solverperf: device-first solver funnel suite (inverted-vs-"
        "legacy parity differential, deterministic heterogeneous lane "
        "seeding, cube-split/merge + exhausted-cube unsat, witness "
        "validation, sprint-cap knob, race-margin histogram; "
        "CPU-only — runs in tier-1, selectable with -m solverperf)",
    )
    config.addinivalue_line(
        "markers",
        "store: cross-run verdict store suite (mythril_tpu/store: "
        "content-addressed entries + config fingerprints, exact-hit "
        "settle at corpus/service admission, fingerprint-diff "
        "incremental re-analysis differential, corrupt-entry refusal, "
        "concurrent writers, --no-store parity; CPU-only — runs in "
        "tier-1, selectable with -m store)",
    )
    config.addinivalue_line(
        "markers",
        "chaos: crash-safe serving suite (durable job journal append/"
        "replay, recovery re-admission with store dedupe, poison-job "
        "quarantine strike escalation, tier circuit-breaker "
        "transitions and ladder fallback, journal-fault degradation; "
        "CPU-only — runs in tier-1, selectable with -m chaos; the "
        "subprocess SIGKILL harness is tools/chaos_smoke.py via "
        "[testenv:chaos])",
    )
    config.addinivalue_line(
        "markers",
        "fleet: federated serving suite (mythril_tpu/fleet: health-"
        "routed admission over N replicas, replica-death failover "
        "with idempotency-keyed reroute dedupe through the shared "
        "verdict store, drain-time frontier handoff, fleet-wide load "
        "shedding with Retry-After, front journal recovery; CPU-only, "
        "engine-less servers — runs in tier-1, selectable with "
        "-m fleet; the subprocess kill-one-replica harness is "
        "tools/fleet_smoke.py via [testenv:fleet])",
    )
    config.addinivalue_line(
        "markers",
        "chainstream: reorg-safe chain-head streaming suite "
        "(mythril_tpu/chainstream: multi-endpoint RPC failover with "
        "death breakers + quorum heads, crash-safe cursor journal "
        "with reorg rollback, line-rate static triage, "
        "fired/retracted/superseded alert log, fleet survivor "
        "handoff with content-derived idempotency keys; scripted "
        "in-process fake chain, no network — runs in tier-1, "
        "selectable with -m chainstream; the subprocess "
        "SIGKILL+reorg harness is tools/chainstream_smoke.py via "
        "[testenv:chainstream])",
    )
    config.addinivalue_line(
        "markers",
        "compileplane: persistent AOT compile plane suite "
        "(mythril_tpu/compileplane: artifact-cache roundtrip + "
        "checksum/fingerprint/schema refusal, bake->fresh-plane load "
        "bit-identical differential, MYTHRIL_NO_AOT fallback parity, "
        "concurrent writers, LRU eviction, TIER_COMPILEPLANE breaker "
        "fallback, pack-warmed service boot ordering; CPU-only — runs "
        "in tier-1, selectable with -m compileplane; the subprocess "
        "SIGKILL+restart harness is tools/compileplane_smoke.py via "
        "[testenv:compileplane])",
    )
    config.addinivalue_line(
        "markers",
        "taint: taint & value-set static layer suite (attacker-taint "
        "fixpoint goldens, semantic screen soundness sweep over every "
        "module positive fixture, static-answer triage differential, "
        "taint lint checks, routing schema back-compat; host-only, "
        "fast — runs in tier-1, selectable with -m taint)",
    )
    config.addinivalue_line(
        "markers",
        "linker: cross-contract static linker suite (analysis/static/"
        "callgraph + linkset: call-site provenance goldens, SCC escape "
        "widening, proxy pairing + storage-collision diff, the linked-"
        "fingerprint store-invalidation differential, `myth graph` "
        "JSON golden, the four link lint checks, routing v3->v4 "
        "back-compat; host-only, fast — runs in tier-1, selectable "
        "with -m linker)",
    )
    config.addinivalue_line(
        "markers",
        "router: learned tier-ladder router + solver self-tuning suite "
        "(mythril_tpu/routing: artifact roundtrip/refusal/fallback, "
        "train->eval determinism golden, routed service admission + "
        "router-off parity + in-flight promotion-on-overrun, the "
        "tuned-overrides replay-agreement gate and tune --watch loop, "
        "cost-informed fleet replica choice differential; host-only, "
        "fast — runs in tier-1, selectable with -m router)",
    )


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest

    if config.getoption("-m"):
        return
    skip_slow = _pytest.mark.skip(reason="slow golden analysis; use -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
