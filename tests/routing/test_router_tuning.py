"""The second flywheel loop: tuned-v<N>.json PORTFOLIO_DEFAULTS
override artifacts (save/load/refusal/install) and the `myth solverlab
tune --watch` incremental loop — a sweep winner only promotes after
beating the committed defaults AND a 100% host-replay agreement gate;
one flipped verdict blocks promotion unconditionally.

The solver internals (`solverlab._rebuild/_replay_host/_replay_device/
_classify`, `tune_corpus`, `querylog.load_corpus`) are monkeypatched —
this file tests the promotion machinery, not the solvers.
"""

from __future__ import annotations

import json

import pytest

from mythril_tpu import routing
from mythril_tpu.laser.smt.solver import portfolio
from mythril_tpu.routing.tuning import load_tuned_file, tune_watch

pytestmark = pytest.mark.router

KNOB = sorted(portfolio.PORTFOLIO_DEFAULTS)[0]


@pytest.fixture(autouse=True)
def factory_defaults():
    yield
    portfolio.reset_tuned_defaults()


def _gate(n=4):
    return {"queries": n, "agree": n, "disagree": 0, "pass": True}


# -- artifact layer ----------------------------------------------------
def test_tuned_roundtrip_and_install(tmp_path):
    original = portfolio.PORTFOLIO_DEFAULTS[KNOB]
    path = routing.save_tuned(
        str(tmp_path), {KNOB: original + 2}, gate=_gate()
    )
    doc = load_tuned_file(path)
    assert doc["overrides"] == {KNOB: original + 2}
    assert doc["gate"]["pass"] is True
    assert routing.maybe_install_tuned(str(tmp_path)) == 1
    assert portfolio.PORTFOLIO_DEFAULTS[KNOB] == original + 2
    assert portfolio.tuned_version() == 1
    portfolio.reset_tuned_defaults()
    assert portfolio.PORTFOLIO_DEFAULTS[KNOB] == original
    assert portfolio.tuned_version() == 0


def test_save_tuned_rejects_unknown_knob(tmp_path):
    with pytest.raises(ValueError):
        routing.save_tuned(
            str(tmp_path), {"no_such_knob": 1}, gate=_gate()
        )


def test_unknown_knob_artifact_refused_on_load(tmp_path):
    """A newer writer's knob set must refuse, not partially apply."""
    path = tmp_path / "tuned-v1.json"
    original = portfolio.PORTFOLIO_DEFAULTS[KNOB]
    saved = routing.save_tuned(
        str(tmp_path), {KNOB: original + 1}, gate=_gate()
    )
    doc = json.loads(open(saved).read())
    doc["overrides"]["knob_from_the_future"] = 7
    from mythril_tpu.routing.artifact import checksum_doc

    doc["checksum"] = checksum_doc(doc)  # checksum VALID — knob unknown
    path.write_text(json.dumps(doc))
    with pytest.raises(routing.ArtifactRefused) as refused:
        load_tuned_file(str(path))
    assert refused.value.reason == "unknown-knob"
    assert routing.maybe_install_tuned(str(tmp_path)) is None
    assert portfolio.PORTFOLIO_DEFAULTS[KNOB] == original


def test_corrupted_tuned_refused_and_defaults_stand(tmp_path):
    original = portfolio.PORTFOLIO_DEFAULTS[KNOB]
    saved = routing.save_tuned(
        str(tmp_path), {KNOB: original + 1}, gate=_gate()
    )
    doc = json.loads(open(saved).read())
    doc["overrides"][KNOB] = original + 999  # checksum now stale
    (tmp_path / "tuned-v1.json").write_text(json.dumps(doc))
    assert routing.latest_tuned(str(tmp_path)) is None
    assert routing.maybe_install_tuned(str(tmp_path)) is None
    assert portfolio.PORTFOLIO_DEFAULTS[KNOB] == original


def test_newer_tuned_schema_refused(tmp_path):
    saved = routing.save_tuned(
        str(tmp_path), {KNOB: portfolio.PORTFOLIO_DEFAULTS[KNOB]},
        gate=_gate(),
    )
    doc = json.loads(open(saved).read())
    doc["schema_version"] = routing.TUNED_SCHEMA_VERSION + 1
    (tmp_path / "tuned-v1.json").write_text(json.dumps(doc))
    with pytest.raises(routing.ArtifactRefused) as refused:
        load_tuned_file(saved)
    assert refused.value.reason == "schema"


# -- the replay-agreement gate -----------------------------------------
def _wire_solverlab(monkeypatch, verdicts):
    """Stub the replay internals: `verdicts` maps query sha to the
    _classify outcome its replay should produce."""
    from mythril_tpu.analysis import solverlab

    monkeypatch.setattr(solverlab, "_rebuild", lambda art: art["sha"])
    monkeypatch.setattr(
        solverlab, "_replay_host", lambda lowered, timeout_ms: ("sat", 1.0)
    )
    monkeypatch.setattr(
        solverlab,
        "_replay_device",
        lambda lowered, candidates, steps: (("sat", 1.0), 0.0),
    )
    monkeypatch.setattr(
        solverlab, "_classify", lambda host, tuned, _v=verdicts: "agree"
    )
    return solverlab


def test_gate_passes_on_full_agreement(monkeypatch):
    _wire_solverlab(monkeypatch, {})
    corpus = [{"sha": f"q{i}"} for i in range(5)]
    gate = routing.gate_overrides(corpus, {KNOB: 1})
    assert gate["pass"] is True
    assert gate["agree"] == 5 and gate["disagree"] == 0


def test_single_disagreement_fails_the_gate(monkeypatch):
    from mythril_tpu.analysis import solverlab

    _wire_solverlab(monkeypatch, {})
    flip = {"q2"}
    monkeypatch.setattr(
        solverlab,
        "_classify",
        lambda host, tuned: "disagree" if host == "FLIP" else "agree",
    )
    monkeypatch.setattr(
        solverlab,
        "_replay_host",
        lambda lowered, timeout_ms: "FLIP" if lowered in flip else "sat",
    )
    corpus = [{"sha": f"q{i}"} for i in range(5)]
    gate = routing.gate_overrides(corpus, {KNOB: 1})
    assert gate["pass"] is False
    assert gate["disagree"] == 1 and gate["agree"] == 4
    assert gate["failures"][0]["sha"] == "q2"


def test_incomplete_answers_do_not_block_promotion(monkeypatch):
    from mythril_tpu.analysis import solverlab

    _wire_solverlab(monkeypatch, {})
    monkeypatch.setattr(
        solverlab, "_classify", lambda host, tuned: "incomplete"
    )
    gate = routing.gate_overrides([{"sha": "q0"}], {KNOB: 1})
    assert gate["incomplete"] == 1 and gate["disagree"] == 0
    assert gate["pass"] is True  # honest unknowns cost wall, not soundness


def test_empty_corpus_never_passes(monkeypatch):
    _wire_solverlab(monkeypatch, {})
    assert routing.gate_overrides([], {KNOB: 1})["pass"] is False


# -- the watch loop ----------------------------------------------------
def _wire_watch(monkeypatch, corpora, beats=True, agree=True):
    """Stub the sweep + replay stack under tune_watch: `corpora` is
    the sequence of corpus snapshots successive rounds observe."""
    from mythril_tpu.analysis import solverlab
    from mythril_tpu.observe import querylog

    snapshots = iter(corpora)
    last = {"corpus": corpora[-1]}

    def load_corpus(directory, reason=None, origin=None):
        try:
            last["corpus"] = next(snapshots)
        except StopIteration:
            pass
        return last["corpus"]

    monkeypatch.setattr(querylog, "load_corpus", load_corpus)
    monkeypatch.setattr(
        solverlab,
        "tune_corpus",
        lambda corpus, **kw: {
            "beats_baseline": beats,
            "best": {"knobs": {KNOB: portfolio.PORTFOLIO_DEFAULTS[KNOB] + 1},
                     "loss": 0.5},
        },
    )
    _wire_solverlab(monkeypatch, {})
    if not agree:
        monkeypatch.setattr(
            solverlab, "_classify", lambda host, tuned: "disagree"
        )


def test_watch_promotes_after_gate(tmp_path, monkeypatch):
    corpus = [{"sha": f"q{i}"} for i in range(10)]
    _wire_watch(monkeypatch, [corpus])
    naps = []
    out = tune_watch(
        "unused", str(tmp_path), rounds=1, sleep=naps.append
    )
    assert out["sweeps"] == 1
    assert out["promoted"] and out["promoted"].endswith("tuned-v1.json")
    assert out["rounds"][0]["gate"]["pass"] is True
    doc = load_tuned_file(out["promoted"])
    assert doc["overrides"] == {KNOB: portfolio.PORTFOLIO_DEFAULTS[KNOB] + 1}
    assert naps == []  # bounded rounds never slept


def test_watch_gate_failure_blocks_promotion(tmp_path, monkeypatch):
    corpus = [{"sha": f"q{i}"} for i in range(10)]
    _wire_watch(monkeypatch, [corpus], agree=False)
    out = tune_watch("unused", str(tmp_path), rounds=1, sleep=lambda s: None)
    assert out["sweeps"] == 1
    assert out["promoted"] is None
    assert out["rounds"][0]["gate"]["pass"] is False
    assert routing.latest_tuned(str(tmp_path)) is None


def test_watch_loser_never_gated(tmp_path, monkeypatch):
    corpus = [{"sha": "q0"}]
    _wire_watch(monkeypatch, [corpus], beats=False)
    out = tune_watch("unused", str(tmp_path), rounds=1, sleep=lambda s: None)
    assert out["sweeps"] == 1
    assert out["promoted"] is None
    assert "gate" not in out["rounds"][0]  # the sweep lost; no replay paid


def test_watch_waits_for_min_new(tmp_path, monkeypatch):
    """Round 1 always sweeps; round 2 sees too few fresh queries and
    skips; round 3 crosses min_new and sweeps again — the incremental
    contract (+ per-sweep seed advance) in one run."""
    from mythril_tpu.analysis import solverlab

    base = [{"sha": f"q{i}"} for i in range(8)]
    trickle = base + [{"sha": "q8"}]
    flood = trickle + [{"sha": f"r{i}"} for i in range(4)]
    _wire_watch(monkeypatch, [base, trickle, flood])
    seeds = []
    original = solverlab.tune_corpus

    def spy(corpus, **kw):
        seeds.append(kw.get("seed"))
        return original(corpus, **kw)

    monkeypatch.setattr(solverlab, "tune_corpus", spy)
    naps = []
    out = tune_watch(
        "unused", str(tmp_path), interval_s=7.0, min_new=3, rounds=3,
        sleep=naps.append,
    )
    assert out["sweeps"] == 2
    # skipped round 2's q8 stays "new" until a sweep consumes it
    assert [r["new"] for r in out["rounds"]] == [8, 1, 5]
    assert seeds == [1, 2]  # tune_seed advances per SWEEP, not round
    assert naps == [7.0, 7.0]
    # two promotions: the second sweep versioned on top of the first
    assert out["promoted"].endswith("tuned-v2.json")
