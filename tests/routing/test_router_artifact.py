"""Router artifact + cost-model suite (mythril_tpu/routing): the
train->save->load->decide roundtrip, the refusal ladder (corrupted /
newer-schema / wrong-kind / renamed artifacts are REFUSED with a
counted reason and the loader falls back to the newest older artifact
or to heuristics — never a misload), train->eval determinism on a
synthetic JSONL golden, and the observe-layer satellites (streaming
read, bounded tail, the routed-/promoted- route vocabulary).

Host-only, numpy-only, sub-second — runs in tier-1 via the `router`
marker.
"""

from __future__ import annotations

import json
import math

import pytest

from mythril_tpu import routing
from mythril_tpu.observe.registry import registry
from mythril_tpu.observe.routing import (
    iter_records,
    outcome_for,
    read_records,
    tail_records,
)
from mythril_tpu.routing.artifact import load_router_file, router_versions

pytestmark = pytest.mark.router


def synthetic_records(n=60, seed=3):
    """A deterministic mixed log, linearly separable on size: cheap
    host-walks (fast), heavy device-owned runs, and the mis-route
    class the flywheel trains on — heavy contracts that went to the
    host tier and paid for it (what promotion traffic looks like)."""
    records = []
    for i in range(n):
        kind = i % 3  # 0: cheap host, 1: heavy device, 2: heavy host
        heavy = 0 if kind == 0 else 1
        jitter = ((i * seed * 2654435761) % 1000) / 1000.0
        if kind == 0:
            route, wall = "host-walk", 0.1 + jitter / 10
        elif kind == 1:
            route, wall = "device-owned", 2.0 + jitter
        else:
            route, wall = "host-walk", 8.0 + jitter
        features = {
            "code_bytes": 200 + 4000 * heavy + int(40 * jitter),
            "storage_op_density": 0.02 + 0.1 * heavy,
            "call_op_density": 0.01,
            "cfg_blocks": 4 + 60 * heavy,
            "cfg_reachable_blocks": 4 + 50 * heavy,
            "instructions": 100 + 2000 * heavy,
            "selectors": 2 + 8 * heavy,
            "dead_selectors": 0,
            "dead_directions": 0,
            "modules_screened": 3,
            "taint_density": 0.1 * heavy,
            "tainted_sinks": 2 * heavy,
            "sink_counts": None,
            "resolved_call_targets": heavy,
            "fingerprints": 1,
            "static_answerable": 0,
            "link_out_degree": heavy,
            "link_resolved_degree": heavy,
            "link_is_proxy": 0,
            "link_proxy_kind": None,
            "link_delegatecall_sites": 0,
            "link_escape_density": 0.0,
            "phase_bucket_pruned": 0,
            "fuse_profitable": heavy,
            "phase_bucket": "bucket",
        }
        records.append({
            "schema_version": 4,
            "contract": f"c{i}",
            "code_hash": f"{i:064x}",
            "features": features,
            "outcome": {
                "route": route,
                "wall_s": wall,
                "issues": 0,
                "states": 10,
                "complete": True,
                "error": None,
            },
            "journey_id": f"j{i}",
        })
    return records


@pytest.fixture()
def records():
    return synthetic_records()


@pytest.fixture()
def artifact_dir(tmp_path, records):
    model = routing.train_model(records)
    routing.save_router(str(tmp_path), model)
    return tmp_path


# -- roundtrip ---------------------------------------------------------
def test_train_save_load_decide_roundtrip(artifact_dir, records):
    router = routing.load_router(str(artifact_dir))
    assert router is not None
    assert router.version == 1
    assert set(router.routes()) == {"host-walk", "device-waves"}
    cheap = records[0]["features"]
    heavy = records[1]["features"]
    assert router.decide(cheap).route == "host-walk"
    decision = router.decide(heavy)
    assert decision.route == "device-waves"
    # the decision carries the full priced table + a usable budget
    assert decision.cost("host-walk") is not None
    assert decision.budget_s() >= 0.25


def test_versions_increment_and_newest_wins(artifact_dir, records):
    model = routing.train_model(records)
    routing.save_router(str(artifact_dir), model)
    versions = router_versions(str(artifact_dir))
    assert [v for v, _p in versions] == [2, 1]
    assert routing.load_router(str(artifact_dir)).version == 2


def test_decide_respects_offered_tiers(artifact_dir, records):
    router = routing.load_router(str(artifact_dir))
    heavy = records[1]["features"]
    forced = router.decide(heavy, tiers=["host-walk"])
    assert forced.route == "host-walk"
    assert router.decide(heavy, tiers=["no-such-tier"]) is None


# -- refusal ladder ----------------------------------------------------
def _corrupt(path):
    doc = json.loads(path.read_text())
    doc["model"]["trained_rows"] = 10_000  # checksum now stale
    path.write_text(json.dumps(doc))


def test_corrupted_artifact_falls_back_to_older(artifact_dir, records):
    model = routing.train_model(records)
    v2 = routing.save_router(str(artifact_dir), model)
    _corrupt(artifact_dir / "router-v2.json")
    base = registry().value("mtpu_router_refused_total", reason="checksum")
    router = routing.load_router(str(artifact_dir))
    assert router is not None and router.version == 1  # fell back
    assert registry().value(
        "mtpu_router_refused_total", reason="checksum"
    ) == base + 1
    assert v2.endswith("router-v2.json")


def test_all_refused_means_heuristics_not_misload(artifact_dir):
    _corrupt(artifact_dir / "router-v1.json")
    assert routing.load_router(str(artifact_dir)) is None
    assert registry().value("mtpu_router_artifact_version") == 0


def test_newer_schema_refused(artifact_dir):
    path = artifact_dir / "router-v1.json"
    doc = json.loads(path.read_text())
    doc["schema_version"] = routing.ROUTER_SCHEMA_VERSION + 1
    path.write_text(json.dumps(doc))
    with pytest.raises(routing.ArtifactRefused) as refused:
        load_router_file(str(path))
    assert refused.value.reason == "schema"
    assert routing.load_router(str(artifact_dir)) is None


def test_wrong_kind_refused(artifact_dir):
    path = artifact_dir / "router-v1.json"
    doc = json.loads(path.read_text())
    doc["kind"] = "mtpu-kernel-pack"
    path.write_text(json.dumps(doc))
    with pytest.raises(routing.ArtifactRefused):
        load_router_file(str(path))


def test_renamed_artifact_version_mismatch_refused(artifact_dir):
    (artifact_dir / "router-v1.json").rename(
        artifact_dir / "router-v7.json"
    )
    with pytest.raises(routing.ArtifactRefused) as refused:
        load_router_file(str(artifact_dir / "router-v7.json"))
    assert refused.value.reason == "version"


def test_junk_json_refused(artifact_dir):
    (artifact_dir / "router-v1.json").write_text("{nope")
    assert routing.load_router(str(artifact_dir)) is None


def test_missing_directory_is_heuristics(tmp_path):
    assert routing.load_router(str(tmp_path / "absent")) is None
    assert routing.load_router(None) is None


# -- determinism golden ------------------------------------------------
def test_train_is_deterministic(records):
    a = routing.train_model(records)
    b = routing.train_model(list(records))
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_train_eval_deterministic_golden(artifact_dir, records):
    router = routing.load_router(str(artifact_dir))
    one = routing.evaluate_log(records, router)
    two = routing.evaluate_log(records, router)
    assert one == two
    assert one["records"] == len(records)
    assert one["scored"] == len(records)
    assert one["regret_s"] >= 0.0
    assert 0.0 <= one["oracle_agreement"] <= 1.0
    # the separable synthetic corpus: two thirds walked on the host
    host = one["per_route"]["host-walk"]
    assert host["n"] == 2 * len(records) // 3
    assert one["per_route"]["device-waves"]["n"] == len(records) // 3


def test_train_refuses_empty_log():
    with pytest.raises(ValueError):
        routing.train_model([])
    with pytest.raises(ValueError):
        # triage-tier routes carry no trainable signal
        routing.train_model([
            {"outcome": {"route": "store-hit", "wall_s": 0.001}},
            {"outcome": {"route": "static-answer", "wall_s": 0.001}},
        ])


def test_explain_record_names_drivers(artifact_dir, records):
    router = routing.load_router(str(artifact_dir))
    report = routing.explain_record(records[0], router)
    assert report["logged_route"] == "host-walk"
    assert report["router_version"] == 1
    assert set(report["expected"]) == {"host-walk", "device-waves"}
    for rows in report["attributions"].values():
        assert rows and "feature" in rows[0]


def test_route_normalization_feeds_the_flywheel():
    assert routing.normalize_route("routed-host-walk") == "host-walk"
    assert routing.normalize_route("promoted-device-waves") == "device-waves"
    assert routing.normalize_route("device-owned") == "device-waves"
    assert routing.normalize_route("store-hit") is None
    assert routing.normalize_route(None) is None


# -- observe satellites ------------------------------------------------
def test_outcome_for_routed_and_promoted_vocabulary():
    base = {"issues": [], "states": 3, "error": None, "wall_s": 0.2}
    routed = outcome_for(dict(base, routed="host-walk"))
    assert routed["route"] == "routed-host-walk"
    assert routed["wall_s"] == 0.2
    promoted = outcome_for(
        dict(base, routed="host-walk", promoted="device-waves")
    )
    assert promoted["route"] == "promoted-device-waves"
    # schema stays v4: plain results keep today's vocabulary
    assert outcome_for(dict(base))["route"] == "host-walk"
    assert outcome_for(dict(base, owned=True))["route"] == "device-owned"


def _write_log(path, records, junk=True):
    with open(path, "w") as fp:
        for i, rec in enumerate(records):
            fp.write(json.dumps(rec) + "\n")
            if junk and i == 1:
                fp.write("not json\n\n")  # tolerated, skipped


def test_tail_records_matches_streaming_tail(tmp_path, records):
    path = str(tmp_path / "routing_features.jsonl")
    _write_log(path, records)
    assert tail_records(path, 10) == read_records(path)[-10:]
    assert tail_records(path, 10_000) == read_records(path)
    assert tail_records(path, 0) == []
    assert list(iter_records(path)) == read_records(path)


def test_read_records_bound(tmp_path, records):
    path = str(tmp_path / "routing_features.jsonl")
    _write_log(path, records, junk=False)
    assert len(read_records(path, n=7)) == 7
    assert read_records(path, n=7) == records[-7:]


def test_budget_scales_with_predicted_wall():
    d = len(routing.FEATURE_COLUMNS)
    head = {
        "n": 5, "mean_wall_s": 4.0,
        "wall_w": [0.0] * d, "wall_b": math.log1p(4.0),
        "succ_w": [0.0] * d, "succ_b": 30.0,
    }
    doc = {
        "version": 9,
        "model": {
            "features": list(routing.FEATURE_COLUMNS),
            "impute": [0.0] * d, "scale": [1.0] * d,
            "routes": {"host-walk": head}, "trained_rows": 5,
        },
    }
    router = routing.Router(doc)
    decision = router.decide({}, tiers=["host-walk"])
    assert decision.route == "host-walk"
    assert decision.budget_s(slack=3.0) == pytest.approx(12.0, rel=1e-3)
    assert decision.budget_s(slack=0.0) == 0.25  # the floor
