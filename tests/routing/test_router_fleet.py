"""Cost-informed replica choice at the fleet front: without a mounted
router artifact `_candidates` is EXACTLY the historical least-loaded
order (the parity half of the differential); with one, replicas are
priced as expected drain time — (occupancy + 1) x the settle-latency
EWMA `_note_terminal` measures — so a fast replica with a deep queue
beats a slow one with a short queue. No replica processes exist:
`_candidates` is exercised directly against stubbed load/EWMA state.
"""

from __future__ import annotations

import math
import time

import pytest

from mythril_tpu import routing
from mythril_tpu.fleet.front import FleetConfig, FleetFront, FleetJob

pytestmark = [pytest.mark.router, pytest.mark.fleet]

URLS = [f"http://127.0.0.1:{7001 + i}" for i in range(3)]

FLEET_KW = dict(probe_interval_s=30.0, failure_threshold=2, recovery_s=60.0)


class StubReplica:
    def __init__(self, name, load):
        self.name = name
        self.routable = True
        self._load = load

    def load(self):
        return self._load


def front_with(loads, router=None, ewma=None, **over):
    front = FleetFront(FleetConfig(URLS, **dict(FLEET_KW, **over)))
    front.replicas = {
        f"r{i}": StubReplica(f"r{i}", load) for i, load in enumerate(loads)
    }
    front._router = router
    front._settle_ewma = dict(ewma or {})
    return front


def order(front, exclude=None):
    return [r.name for r in front._candidates(exclude=exclude)]


def manual_router(tmp_path):
    d = len(routing.FEATURE_COLUMNS)
    head = {
        "n": 4, "mean_wall_s": 1.0,
        "wall_w": [0.0] * d, "wall_b": math.log1p(1.0),
        "succ_w": [0.0] * d, "succ_b": 30.0,
    }
    model = {
        "features": list(routing.FEATURE_COLUMNS),
        "impute": [0.0] * d, "scale": [1.0] * d,
        "routes": {"host-walk": head}, "trained_rows": 4,
    }
    routing.save_router(str(tmp_path / "router"), model)
    return routing.load_router(str(tmp_path / "router"))


# -- the differential --------------------------------------------------
def test_no_router_is_least_loaded_order():
    front = front_with([2, 0, 1])
    assert order(front) == ["r1", "r2", "r0"]


def test_router_without_samples_is_least_loaded_parity(tmp_path):
    """A freshly mounted router changes NOTHING until real settles
    feed the EWMA — both fronts must route bit-for-bit identically."""
    plain = front_with([2, 0, 1])
    routed = front_with([2, 0, 1], router=manual_router(tmp_path))
    for exclude in (None, "r1", "r0"):
        assert order(plain, exclude) == order(routed, exclude)


def test_router_with_samples_prices_drain_time(tmp_path):
    """r0: 3 queued jobs but 0.1s settles -> drain 0.4s. r1: empty
    but 10s settles -> drain 10s. Least-loaded picks r1 (wrong);
    the cost model picks r0."""
    loads, ewma = [3, 0, 9], {"r0": 0.1, "r1": 10.0, "r2": 0.1}
    assert order(front_with(loads))[0] == "r1"
    routed = front_with(loads, router=manual_router(tmp_path), ewma=ewma)
    assert order(routed) == ["r0", "r2", "r1"]


def test_unsampled_replica_prices_at_fleet_median(tmp_path):
    """r1 has no settle sample: it prices at the fleet median (4.0),
    not at zero — a brand-new replica doesn't vacuum all traffic."""
    routed = front_with(
        [1, 0, 1],
        router=manual_router(tmp_path),
        ewma={"r0": 1.0, "r2": 4.0},
    )
    # r0: 2*1=2; r1: 1*4=4 (median); r2: 2*4=8
    assert order(routed) == ["r0", "r1", "r2"]


def test_exclude_still_honored_under_cost_routing(tmp_path):
    routed = front_with(
        [0, 0, 0],
        router=manual_router(tmp_path),
        ewma={"r0": 1.0, "r1": 2.0, "r2": 3.0},
    )
    assert order(routed, exclude="r0") == ["r1", "r2"]


# -- the EWMA feed -----------------------------------------------------
def _settle(front, replica, latency_s):
    job = FleetJob("33ff")
    job.replica = replica
    job.created_t = time.monotonic() - latency_s
    front._note_terminal(job, {"state": "done"})
    return job


def test_note_terminal_feeds_settle_ewma():
    front = front_with([0, 0, 0])
    _settle(front, "r0", 2.0)
    assert front._settle_ewma["r0"] == pytest.approx(2.0, abs=0.1)
    _settle(front, "r0", 4.0)
    # alpha .3: 0.3*4 + 0.7*2 = 2.6
    assert front._settle_ewma["r0"] == pytest.approx(2.6, abs=0.1)
    assert "r1" not in front._settle_ewma


def test_stats_surfaces_router_block(tmp_path):
    front = FleetFront(
        FleetConfig(URLS, router_dir=str(tmp_path / "missing"), **FLEET_KW)
    )
    block = front.stats()["fleet"]["router"]
    assert block == {"mounted": False, "version": None, "settle_ewma_s": {}}

    routed = FleetFront(FleetConfig(URLS, **FLEET_KW))
    routed._router = manual_router(tmp_path)
    routed._settle_ewma = {"r0": 1.23456}
    block = routed.stats()["fleet"]["router"]
    assert block["mounted"] is True
    assert block["version"] == 1
    assert block["settle_ewma_s"] == {"r0": 1.2346}
