"""The cost-model router at `myth serve` admission, engine-less
(start_engine=False): the routed tier runs on the walk pool straight
from `submit` — a job that settles DONE here provably never saw a
wave dispatch, because the wave thread does not exist.  Covers the
routed fast path, the structural router-off / no-artifact / refused
parity (the submission queues exactly like today), and the in-flight
promotion ladder (`_finalize`): budget overrun or walk error sends a
routed job to the HEAD of the wave queue, once.
"""

from __future__ import annotations

import json
import math
import time

import pytest

from mythril_tpu import routing
from mythril_tpu.observe.registry import registry
from mythril_tpu.service.client import ServiceClient
from mythril_tpu.service.engine import ServiceConfig
from mythril_tpu.service.jobs import Job, JobState
from mythril_tpu.service.server import AnalysisServer

pytestmark = [pytest.mark.router, pytest.mark.service]

#: CALLER; SELFDESTRUCT — a real (fast) host walk with a real issue
KILLABLE = "33ff"

CFG = dict(
    stripes=2,
    lanes_per_stripe=4,
    steps_per_wave=64,
    queue_capacity=4,
    host_walk=True,
)


def manual_model(host_wall, device_wall):
    """A hand-built cost model with flat per-route predictions —
    deterministic routing without depending on trained weights."""
    d = len(routing.FEATURE_COLUMNS)

    def head(wall):
        return {
            "n": 10, "mean_wall_s": wall,
            "wall_w": [0.0] * d, "wall_b": math.log1p(wall),
            "succ_w": [0.0] * d, "succ_b": 30.0,
        }

    return {
        "features": list(routing.FEATURE_COLUMNS),
        "impute": [0.0] * d,
        "scale": [1.0] * d,
        "routes": {
            "host-walk": head(host_wall),
            "device-waves": head(device_wall),
        },
        "trained_rows": 20,
    }


def artifact_dir(tmp_path, host_wall=20.0, device_wall=50.0):
    # host_wall=20 keeps the promotion budget (3x predicted) far above
    # a cold-start walk's wall — promotion is exercised separately
    directory = tmp_path / "router"
    routing.save_router(str(directory), manual_model(host_wall, device_wall))
    return str(directory)


def start_server(**over):
    return AnalysisServer(
        ServiceConfig(**dict(CFG, **over)), start_engine=False
    ).start()


def wait_terminal(client, job_id, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        job = client.job(job_id)
        if job["state"] in ("done", "failed"):
            return job
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never settled: {client.job(job_id)}")


# -- the routed admission tier -----------------------------------------
def test_routed_submission_settles_on_walk_pool(tmp_path):
    srv = start_server(router_dir=artifact_dir(tmp_path))
    try:
        assert srv.engine._router is not None
        client = ServiceClient(srv.url, honor_retry_after=False)
        job_id = client.submit(KILLABLE)
        job = wait_terminal(client, job_id)
        assert job["state"] == "done"
        assert job["routed"] == "host-walk"
        assert "promoted" not in job  # 2-byte walk beat its budget
        report = job["report"]
        assert report["device"]["waves"] == 0  # no wave thread exists
        assert report["issues"]  # the suicide issue came off the walk
        assert report["timings"]["device_s"] == 0.0
    finally:
        srv.close()


def test_router_off_flag_is_todays_ladder(tmp_path):
    """--no-router: same artifact present, flag off — the submission
    queues exactly like today (engine-less: stays queued forever)."""
    srv = start_server(router_dir=artifact_dir(tmp_path), router=False)
    try:
        assert srv.engine._router is None
        client = ServiceClient(srv.url, honor_retry_after=False)
        job = client.job(client.submit(KILLABLE))
        assert job["state"] == "queued"
        assert "routed" not in job
    finally:
        srv.close()


def test_missing_artifact_is_todays_ladder(tmp_path):
    srv = start_server(router_dir=str(tmp_path / "empty"))
    try:
        assert srv.engine._router is None
        client = ServiceClient(srv.url, honor_retry_after=False)
        assert client.job(client.submit(KILLABLE))["state"] == "queued"
    finally:
        srv.close()


def test_refused_artifact_is_todays_ladder(tmp_path):
    directory = artifact_dir(tmp_path)
    path = tmp_path / "router" / "router-v1.json"
    doc = json.loads(path.read_text())
    doc["model"]["trained_rows"] = 999  # checksum now stale
    path.write_text(json.dumps(doc))
    srv = start_server(router_dir=directory)
    try:
        assert srv.engine._router is None  # refused, never mis-loaded
        client = ServiceClient(srv.url, honor_retry_after=False)
        assert client.job(client.submit(KILLABLE))["state"] == "queued"
    finally:
        srv.close()


def test_device_priced_submission_keeps_queue_path(tmp_path):
    """A model that prices the device tier cheaper must leave the
    submission on the wave queue — routing only bypasses the queue
    when the host walk wins."""
    srv = start_server(
        router_dir=artifact_dir(tmp_path, host_wall=50.0, device_wall=0.5)
    )
    try:
        assert srv.engine._router is not None
        client = ServiceClient(srv.url, honor_retry_after=False)
        job = client.job(client.submit(KILLABLE))
        assert job["state"] == "queued"
        assert "routed" not in job
    finally:
        srv.close()


# -- in-flight promotion (_finalize) -----------------------------------
def _routed_job(engine, budget_s, wall_s):
    """Register a fabricated routed job whose walk 'already ran' for
    `wall_s` seconds against a `budget_s` budget."""
    job = Job(KILLABLE)
    engine.queue.register(job)
    job.routed = "host-walk"
    job.route_budget_s = budget_s
    job.started_t = time.monotonic() - wall_s
    job.state = JobState.ANALYZING
    return job


_OUTCOME = {
    "stats": {"waves": 0, "device_steps": 0},
    "covered_branches": [],
    "triggers": {},
    "degraded_lanes": 0,
}


def test_budget_overrun_promotes_to_wave_queue_head(tmp_path):
    srv = start_server(router_dir=artifact_dir(tmp_path))
    try:
        engine = srv.engine
        base = registry().value("mtpu_router_promotions_total")
        job = _routed_job(engine, budget_s=0.5, wall_s=5.0)
        engine._finalize(
            job, None, dict(_OUTCOME),
            host_result={"issues": [], "states": 7, "error": None},
        )
        assert job.promoted == "device-waves"
        assert job.state == JobState.QUEUED
        assert engine.queue._pending[0] is job  # HEAD, not tail
        assert registry().value("mtpu_router_promotions_total") == base + 1
        # regret = wall burnt beyond the predicted budget
        assert registry().value("mtpu_router_regret_seconds_total") > 0
    finally:
        srv.close()


def test_walk_error_promotes_even_under_budget(tmp_path):
    srv = start_server(router_dir=artifact_dir(tmp_path))
    try:
        engine = srv.engine
        job = _routed_job(engine, budget_s=30.0, wall_s=0.1)
        engine._finalize(
            job, None, dict(_OUTCOME),
            host_result={"issues": [], "states": 0, "error": "solver oom"},
        )
        assert job.promoted == "device-waves"
        assert job.error is None  # the error is retried on device, not kept
        assert job.state == JobState.QUEUED
    finally:
        srv.close()


def test_promotion_latches_once(tmp_path):
    """One promotion max: a promoted job that fails its walk again
    settles — it must not ping-pong on the queue forever."""
    srv = start_server(router_dir=artifact_dir(tmp_path))
    try:
        engine = srv.engine
        job = _routed_job(engine, budget_s=0.5, wall_s=5.0)
        engine._finalize(
            job, None, dict(_OUTCOME),
            host_result={"issues": [], "states": 0, "error": "boom"},
        )
        assert job.promoted == "device-waves"
        engine.queue.claim(1)  # the wave tier picks it back up
        engine._finalize(
            job, None, dict(_OUTCOME),
            host_result={"issues": [], "states": 0, "error": "boom"},
        )
        assert job.state != JobState.QUEUED  # settled, no second lap
    finally:
        srv.close()


def test_under_budget_clean_walk_settles_not_promotes(tmp_path):
    srv = start_server(router_dir=artifact_dir(tmp_path))
    try:
        engine = srv.engine
        job = _routed_job(engine, budget_s=30.0, wall_s=0.2)
        engine._finalize(
            job, None, dict(_OUTCOME),
            host_result={"issues": [], "states": 5, "error": None},
        )
        assert job.promoted is None
        assert job.state == JobState.DONE
    finally:
        srv.close()


def test_tuned_artifact_installs_at_engine_init(tmp_path):
    """A tuned-v<N>.json riding in the router directory lands on
    PORTFOLIO_DEFAULTS when the engine mounts the router."""
    from mythril_tpu.laser.smt.solver import portfolio

    directory = artifact_dir(tmp_path)
    knob = next(iter(portfolio.PORTFOLIO_DEFAULTS))
    original = portfolio.PORTFOLIO_DEFAULTS[knob]
    bumped = original + 1
    routing.save_tuned(
        directory, {knob: bumped},
        gate={"queries": 4, "agree": 4, "disagree": 0, "pass": True},
    )
    try:
        srv = start_server(router_dir=directory)
        try:
            assert portfolio.PORTFOLIO_DEFAULTS[knob] == bumped
            assert portfolio.tuned_version() == 1
        finally:
            srv.close()
    finally:
        portfolio.reset_tuned_defaults()
