"""CLI end-to-end tests via subprocess (reference test strategy:
tests/cmd_line_test.py golden runs)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MYTH = os.path.join(REPO, "myth")


def run_myth(*cli_args, timeout=240):
    return subprocess.run(
        [sys.executable, MYTH, *cli_args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )


def test_version():
    out = run_myth("version")
    assert "version" in out.stdout.lower()


def test_version_json():
    out = run_myth("version", "-o", "json")
    assert "version_str" in json.loads(out.stdout)


def test_list_detectors():
    out = run_myth("list-detectors")
    assert "EtherThief" in out.stdout
    assert len(out.stdout.strip().splitlines()) == 14


def test_function_to_hash():
    out = run_myth("function-to-hash", "transfer(address,uint256)")
    assert out.stdout.strip() == "0xa9059cbb"


def test_disassemble():
    out = run_myth("disassemble", "-c", "33ff", "--bin-runtime")
    assert "CALLER" in out.stdout
    assert "SUICIDE" in out.stdout


def test_analyze_detects_selfdestruct_text():
    out = run_myth(
        "analyze",
        "-c",
        "33ff",
        "--bin-runtime",
        "--no-onchain-data",
        "-t",
        "1",
        "--execution-timeout",
        "60",
    )
    assert "Unprotected Selfdestruct" in out.stdout
    assert "SWC ID: 106" in out.stdout
    assert "[ATTACKER]" in out.stdout


def test_analyze_deterministic_solving_flag():
    """--deterministic-solving must be byte-stable: two subprocess
    runs (distinct hash seeds and allocator layouts) produce identical
    reports. (Parity with the default mode's CONTENT is the golden
    harness's job; this pins only cross-run stability of the flag.)"""
    args = (
        "analyze",
        "-c",
        "33ff",
        "--bin-runtime",
        "--no-onchain-data",
        "--deterministic-solving",
        "-t",
        "1",
        "--execution-timeout",
        "60",
    )
    first = run_myth(*args)
    second = run_myth(*args)
    assert "SWC ID: 106" in first.stdout
    assert first.stdout == second.stdout


def test_analyze_json_output():
    out = run_myth(
        "analyze",
        "-c",
        "33ff",
        "--bin-runtime",
        "--no-onchain-data",
        "-t",
        "1",
        "-o",
        "json",
        "--execution-timeout",
        "60",
    )
    data = json.loads(out.stdout)
    assert data["success"] is True
    assert len(data["issues"]) == 1
    assert data["issues"][0]["swc-id"] == "106"


def test_analyze_jsonv2_output():
    out = run_myth(
        "analyze",
        "-c",
        "33ff",
        "--bin-runtime",
        "--no-onchain-data",
        "-t",
        "1",
        "-o",
        "jsonv2",
        "--execution-timeout",
        "60",
    )
    data = json.loads(out.stdout)
    assert data[0]["issues"][0]["swcID"] == "SWC-106"


def test_lint_text_output():
    out = run_myth("lint", "-c", "33ff", "--bin-runtime")
    assert "Static analysis:" in out.stdout
    assert "detector screen:" in out.stdout
    assert out.returncode == 0


def test_lint_json_output():
    from mythril_tpu.analysis.corpusgen import deadweight_contract

    out = run_myth(
        "lint", "-c", deadweight_contract(0), "--bin-runtime", "-o", "json"
    )
    rows = json.loads(out.stdout)
    assert rows[0]["dead_selectors"] == 1
    assert rows[0]["dead_directions"] == 1
    checks = {f["check"] for f in rows[0]["findings"]}
    assert "inert-function" in checks
    assert "dead-branch" in checks


def test_lint_schema_version_and_taint_findings():
    out = run_myth(
        "lint", "-c", "600035565b00", "--bin-runtime", "-o", "json"
    )
    rows = json.loads(out.stdout)
    assert rows[0]["schema_version"] >= 2
    checks = {f["check"] for f in rows[0]["findings"]}
    assert "tainted-jump-target" in checks
    assert out.returncode == 0


def test_lint_fail_on_gates_the_exit_code():
    # the check fires: CI-gate exit 1
    out = run_myth(
        "lint", "-c", "33ff", "--bin-runtime",
        "--fail-on", "unprotected-selfdestruct",
    )
    assert out.returncode == 1
    assert "unprotected-selfdestruct" in out.stdout
    # the check does not fire on this code: exit 0
    out = run_myth(
        "lint", "-c", "33ff", "--bin-runtime",
        "--fail-on", "tainted-delegatecall-target",
    )
    assert out.returncode == 0
    # an unknown check name is an input error, not a silent pass
    out = run_myth(
        "lint", "-c", "33ff", "--bin-runtime", "--fail-on", "no-such-check"
    )
    assert out.returncode == 2


def test_analyze_no_static_prune_flag_parity():
    """--no-static-prune must change nothing but the wasted work: the
    jsonv2 issue list is identical with the prepass on and off."""
    base = (
        "analyze", "-c", "33ff", "--bin-runtime", "--no-onchain-data",
        "-t", "1", "-o", "jsonv2", "--execution-timeout", "60",
    )
    pruned = run_myth(*base)
    unpruned = run_myth(*base, "--no-static-prune")

    def stable(run):
        issues = json.loads(run.stdout)[0]["issues"]
        for issue in issues:
            # wall-clock, differs between any two runs
            issue.get("extra", {}).pop("discoveryTime", None)
        return issues

    assert stable(pruned) == stable(unpruned)
    # and the pruned run's meta carries the static counters
    meta = json.loads(pruned.stdout)[0]["meta"]["mythril_execution_info"]
    assert "static_analysis" in meta
    assert meta["static_analysis"]["modules_skipped"]
    assert "static_analysis" not in json.loads(unpruned.stdout)[0][
        "meta"
    ].get("mythril_execution_info", {})


def test_analyze_clean_contract_no_issues():
    out = run_myth(
        "analyze",
        "-c",
        "6001600055",
        "--bin-runtime",
        "--no-onchain-data",
        "-t",
        "1",
        "--execution-timeout",
        "60",
    )
    assert "No issues were detected" in out.stdout


def test_analyze_statespace_json(tmp_path):
    out_file = tmp_path / "statespace.json"
    run_myth(
        "analyze",
        "-c",
        "600035600757005b00",
        "--bin-runtime",
        "--no-onchain-data",
        "-t",
        "1",
        "-j",
        str(out_file),
        "--execution-timeout",
        "60",
    )
    data = json.loads(out_file.read_text())
    assert data["nodes"]


def test_analyze_graph_html(tmp_path):
    out_file = tmp_path / "graph.html"
    run_myth(
        "analyze",
        "-c",
        "600035600757005b00",
        "--bin-runtime",
        "--no-onchain-data",
        "-t",
        "1",
        "-g",
        str(out_file),
        "--execution-timeout",
        "60",
    )
    assert "vis-network" in out_file.read_text()


def test_corpus_shard_cli_both_hosts():
    """The multi-host workflow end-to-end: the same input analyzed
    with --corpus-shard 0/2 and 1/2 yields exactly one host with the
    finding and one clean empty-shard JSON report; a malformed spec
    errors."""
    base = (
        "analyze", "-c", "33ff", "--bin-runtime", "--no-onchain-data",
        "-t", "1", "-o", "json", "--execution-timeout", "60",
    )
    issues = []
    for shard in ("0/2", "1/2"):
        out = run_myth(*base, "--corpus-shard", shard)
        report = json.loads(out.stdout)
        assert report["success"] is True
        issues.append([i["swc-id"] for i in report["issues"]])
    assert sorted(issues) == [[], ["106"]]

    bad = run_myth(*base, "--corpus-shard", "two/4")
    assert json.loads(bad.stdout)["success"] is False


def test_python_dash_m_entrypoint():
    """`python -m mythril_tpu` is the same CLI as the `myth` script
    (reference parity: `python -m mythril`)."""
    out = subprocess.run(
        [sys.executable, "-m", "mythril_tpu", "version", "-o", "json"],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
    )
    assert out.returncode == 0
    assert "version_str" in json.loads(out.stdout)


def test_python_dash_m_analyze_matches_myth():
    """The module entry drives a real analysis, not just version."""
    out = subprocess.run(
        [
            sys.executable, "-m", "mythril_tpu", "analyze", "-c", "33ff",
            "--bin-runtime", "--no-onchain-data", "-t", "1", "-o", "json",
            "--execution-timeout", "60",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
    )
    report = json.loads(out.stdout)
    assert report["success"] is True
    assert "106" in [i["swc-id"] for i in report["issues"]]


def test_analyze_devices_flag_runs_mesh_scheduler():
    """`myth analyze --devices 2` on a multi-contract input routes
    the prepass through the multi-chip corpus scheduler and still
    reports the single-chip findings (the N-vs-1 CLI surface)."""
    import tempfile

    with tempfile.NamedTemporaryFile(
        "w", suffix=".hex", dir=REPO, delete=False
    ) as fp:
        # two contracts in one codefile is not supported; use one
        # gated-selfdestruct contract: the scheduler path needs >1
        # contract, so this pins flag acceptance + single fallback
        fp.write("604260003560f81c14600d57005b33ff\n")
        path = fp.name
    try:
        out = run_myth(
            "analyze", "-f", path, "--bin-runtime", "--no-onchain-data",
            "-t", "1", "-o", "json", "--devices", "2",
            "--execution-timeout", "60",
        )
        report = json.loads(out.stdout)
        assert report["success"] is True
        assert "106" in [i["swc-id"] for i in report["issues"]]
    finally:
        os.unlink(path)


def test_serve_devices_flag_accepted():
    """`myth serve --devices` is a declared flag (the full mesh serve
    path is pinned in tests/service/test_service_mesh.py)."""
    out = run_myth("serve", "--help")
    assert "--devices" in out.stdout
