"""Federated serving suite (mythril_tpu/fleet): health-routed
admission, replica-death failover with idempotency-keyed reroute
dedupe through the fleet-shared verdict store, drain-time frontier
handoff, fleet-wide shedding with Retry-After, front journal recovery.

Engine-less replicas throughout (start_engine=False, the service-test
idiom): a submitted job is ACKNOWLEDGED and stays queued forever —
exactly the in-flight population a failover must not lose — and the
verdict-store admission tier still settles instantly, which is how a
survivor answers re-routed work in microseconds without this suite
ever paying a device wave. The subprocess SIGKILL harness with real
waves is tools/fleet_smoke.py ([testenv:fleet])."""

import json
import threading
import time
import urllib.request

import pytest

from mythril_tpu.fleet import FleetConfig, FleetFront, FleetServer
from mythril_tpu.fleet.front import FleetJob
from mythril_tpu.service.client import ServiceClient, ServiceError
from mythril_tpu.service.engine import ServiceConfig, _JobTrack
from mythril_tpu.service.jobs import Job, QueueRefusal
from mythril_tpu.service.server import AnalysisServer
from mythril_tpu.store.store import code_hash_hex

pytestmark = [pytest.mark.fleet, pytest.mark.service]

#: CALLER SELFDESTRUCT — module-applicable, never static-answered
KILLABLE = "33ff"
#: storage writer — a second distinct full-path shape
WRITER = "6001600055600060015500"
#: CALLDATALOAD(0) branch into a storage write
BRANCHER = "600035600757005b600160005500"

CFG = dict(
    stripes=2,
    lanes_per_stripe=4,
    queue_capacity=8,
    host_walk=False,
)

#: monitor runs manually (check_replicas) in most tests: no timing
#: races, every probe deterministic
FLEET_KW = dict(
    probe_interval_s=30.0, failure_threshold=2, recovery_s=60.0
)


def replica_server(tmp_path, store=None, **over):
    cfg = dict(CFG, **over)
    if store is not None:
        cfg["store_dir"] = str(store)
    return AnalysisServer(
        ServiceConfig(**cfg), start_engine=False
    ).start()


def enter_drain_window(server):
    """Put an engine-less replica into the mid-drain window: /healthz
    reports draining (ready=1 -> 503), admission refuses, but nothing
    has been checkpointed yet — the state a front rebalances from.
    `_drained` is pre-set so the fixture close() never blocks waiting
    on a wave thread that was never started."""
    server.engine._draining = True
    server.engine._drained.set()


def kill(server):
    """The in-process SIGKILL stand-in: the HTTP listener vanishes
    mid-flight — every later connection is refused, nothing is
    drained, nothing checkpointed."""
    server._httpd.shutdown()
    server._httpd.server_close()


def bank(server, code_hex, issues=None):
    """Write `code_hex`'s verdict into the replica's (shared) store
    the way a completed walk on ANY replica would have."""
    engine = server.engine
    engine.vstore.put(
        code_hash_hex(code_hex),
        engine._config_fp,
        issues=issues or [{"title": "banked", "swc-id": "106"}],
    )


# ---------------------------------------------------------------------------
# routing respects health state
# ---------------------------------------------------------------------------
def test_routing_skips_draining_replica(tmp_path):
    a = replica_server(tmp_path)
    b = replica_server(tmp_path)
    front = FleetFront(FleetConfig([a.url, b.url], **FLEET_KW)).start()
    try:
        # r0 enters the mid-drain window: /healthz?ready=1 says 503
        enter_drain_window(a)
        front.check_replicas()
        assert not front.replicas["r0"].routable
        assert front.replicas["r0"].alive  # answered: alive, not dead
        for i in range(4):
            job, _ = front.submit_ex(KILLABLE, idempotency_key=f"k{i}")
            assert job.replica == "r1"
        assert front.replicas["r1"].routed == 4
        assert front.replicas["r0"].routed == 0
    finally:
        front.close()
        a.close()
        b.close()


def test_least_loaded_striping(tmp_path):
    a = replica_server(tmp_path)
    b = replica_server(tmp_path)
    front = FleetFront(FleetConfig([a.url, b.url], **FLEET_KW)).start()
    try:
        for i in range(6):
            front.submit_ex(KILLABLE, idempotency_key=f"s{i}")
            front.check_replicas()  # refresh occupancy between routes
        # both replicas carry work: striping, not pile-on
        assert front.replicas["r0"].routed >= 1
        assert front.replicas["r1"].routed >= 1
    finally:
        front.close()
        a.close()
        b.close()


def test_fleet_shed_when_nobody_routable(tmp_path):
    a = replica_server(tmp_path)
    front = FleetFront(FleetConfig([a.url], **FLEET_KW)).start()
    try:
        enter_drain_window(a)
        front.check_replicas()
        with pytest.raises(QueueRefusal) as refusal:
            front.submit(KILLABLE)
        assert refusal.value.reason == "saturated"
        assert front.shed == 1
        health = front.health()
        assert health["state"] == "redlined"
        assert "fleet-saturated" in health["reasons"]
    finally:
        front.close()
        a.close()


# ---------------------------------------------------------------------------
# replica death: failover with zero acknowledged-job loss
# ---------------------------------------------------------------------------
def test_kill_one_replica_zero_acknowledged_loss(tmp_path):
    store = tmp_path / "store"
    a = replica_server(tmp_path, store=store)
    b = replica_server(tmp_path, store=store)
    front = FleetFront(FleetConfig([a.url, b.url], **FLEET_KW)).start()
    try:
        codes = [KILLABLE, WRITER, BRANCHER]
        jobs = []
        for i, code in enumerate(codes * 2):  # 6 acknowledged jobs
            job, _ = front.submit_ex(code, idempotency_key=f"ack{i}")
            jobs.append(job)
        dead_name = jobs[0].replica
        victims = [j for j in jobs if j.replica == dead_name]
        assert victims, "striping should land work on both replicas"
        dead, survivor = (a, b) if dead_name == "r0" else (b, a)
        # the fleet-shared store already holds every verdict (some
        # other replica computed them earlier)
        for code in codes:
            bank(survivor, code)
        kill(dead)
        for _ in range(3):  # breaker wants 2 consecutive failures
            front.check_replicas()
        assert not front.replicas[dead_name].alive
        # zero acknowledged-job loss: the victims settle through the
        # survivor's store tier (microseconds); the non-victims are
        # still safely queued on their LIVE replica (engine-less
        # servers never run waves — polling them would only wait out
        # the long-poll budget)
        for job in jobs:
            if job in victims:
                doc = front.report(job.id, wait_s=10.0)
                assert doc["state"] == "done", doc
                assert doc.get("rerouted") is True
                assert doc.get("reroute_deduped") is True
                assert doc["report"]["issues"], doc
            else:
                doc = front.job_doc(job.id)
                assert doc["state"] == "queued", doc
                assert front.replicas[doc["replica"]].alive
        stats = front.stats()["fleet"]
        assert stats["failovers"] == 1
        assert stats["rerouted"] == len(victims)
        assert stats["reroute_deduped"] == len(victims)
        health = front.health()
        assert f"replica-lost:{dead_name}" in health["reasons"]
        assert "fleet-degraded" in health["reasons"]
        assert health["ready"] is True  # the survivor still serves
    finally:
        front.close()
        a.close()
        b.close()


def test_idempotent_submit_dedupes_at_the_front(tmp_path):
    a = replica_server(tmp_path)
    front = FleetFront(FleetConfig([a.url], **FLEET_KW)).start()
    try:
        one, dd1 = front.submit_ex(KILLABLE, idempotency_key="same")
        two, dd2 = front.submit_ex(KILLABLE, idempotency_key="same")
        assert not dd1 and dd2
        assert one.id == two.id
        assert front.deduped == 1
        # only ONE remote job exists
        assert a.engine.queue.get(one.remote_id) is not None
        assert (
            a.engine.queue.jobs_by_state().get("queued", 0) == 1
        )
    finally:
        front.close()
        a.close()


def test_recovered_replica_rejoins_and_second_death_fails_over(tmp_path):
    """A replica that comes BACK clears its failed-over latch: the
    next death triggers a fresh failover instead of being ignored."""
    a = replica_server(tmp_path)
    b = replica_server(tmp_path)
    front = FleetFront(
        FleetConfig(
            [a.url, b.url], probe_interval_s=30.0,
            failure_threshold=2, recovery_s=0.05,
        )
    ).start()
    try:
        kill(b)
        for _ in range(3):
            front.check_replicas()
        assert front.failovers == 1
        assert "r1" in front._failed_over
        # r1 restarts on a fresh port = a fresh server object; rebind
        # the front's URL view to it (the operator would restart on
        # the SAME port; the front only cares that probes succeed)
        b2 = replica_server(tmp_path)
        rep = front.replicas["r1"]
        rep.url = b2.url
        rep.probe_client = ServiceClient(
            b2.url, timeout_s=2.0, retries=0, honor_retry_after=False
        )
        rep.data = ServiceClient(
            b2.url, timeout_s=15.0, retries=1, honor_retry_after=False
        )
        time.sleep(0.06)  # past recovery_s: breaker half-opens
        front.check_replicas()
        assert rep.alive and rep.routable
        assert "r1" not in front._failed_over
        kill(b2)
        for _ in range(3):
            front.check_replicas()
        assert front.failovers == 2
    finally:
        front.close()
        a.close()


# ---------------------------------------------------------------------------
# frontier export / seed
# ---------------------------------------------------------------------------
def test_frontier_export_guard_and_shape(tmp_path):
    a = replica_server(tmp_path)
    client = ServiceClient(a.url, retries=0, honor_retry_after=False)
    try:
        client.submit(KILLABLE, idempotency_key="f1")
        with pytest.raises(ServiceError) as refused:
            client.frontier_export()
        assert refused.value.status == 409
        doc = client.frontier_export(force=True)
        assert doc["schema_version"] == 1
        assert len(doc["jobs"]) == 1
        row = doc["jobs"][0]
        assert row["idempotency_key"] == "f1"
        assert row["code"] == KILLABLE
        assert row["state"] == "queued"
        assert set(row["params"]) == {
            "max_waves", "deadline_s", "host_walk", "lanes",
        }
        # a queued job has no track: the frontier is just the code
        assert row["frontier"]["code_hex"] == KILLABLE
    finally:
        a.close()


def test_frontier_http_roundtrip_seeds_the_new_job(tmp_path):
    """Export from a draining replica, resubmit to another with the
    frontier attached: the new Job carries it and a track built from
    that job continues the donor's coverage."""
    a = replica_server(tmp_path)
    b = replica_server(tmp_path)
    try:
        client_a = ServiceClient(a.url, retries=0)
        client_a.submit(BRANCHER, idempotency_key="h1")
        enter_drain_window(a)
        export = ServiceClient(a.url, retries=0).frontier_export()
        assert export["draining"] is True
        row = export["jobs"][0]
        # enrich the frontier the way a resident track would have
        frontier = dict(
            row["frontier"],
            covered=[[7, True], [7, False]],
            parent_inputs=["ff" * 8],
        )
        payload = ServiceClient(b.url, retries=0).submit_ex(
            BRANCHER, idempotency_key="h1", frontier=frontier
        )
        remote = b.engine.queue.get(payload["job_id"])
        assert remote.frontier == frontier
        track = _JobTrack(remote, [0], [0, 1], 68)
        assert (7, True) in track.covered
        assert (7, False) in track.covered
        assert b"\xff" * 8 in track.corpus
        assert track.frontier_seeded
    finally:
        a.close()
        b.close()


def test_track_export_frontier_roundtrips():
    job = Job(code_hex=BRANCHER)
    track = _JobTrack(job, [0], [0, 1], 68)
    track.covered = {(7, True)}
    track.corpus.append(b"\x01\x02")
    doc = track.export_frontier()
    assert doc["code_hex"] == BRANCHER
    assert [7, True] in doc["covered"]
    assert "0102" in doc["parent_inputs"]
    # seed it into a fresh track: coverage + corpus continue
    job2 = Job(code_hex=BRANCHER, frontier=doc)
    track2 = _JobTrack(job2, [0], [0, 1], 68)
    assert (7, True) in track2.covered
    assert b"\x01\x02" in track2.corpus


def test_draining_replica_hands_jobs_to_survivor(tmp_path):
    a = replica_server(tmp_path)
    b = replica_server(tmp_path)
    front = FleetFront(FleetConfig([a.url, b.url], **FLEET_KW)).start()
    try:
        job, _ = front.submit_ex(KILLABLE, idempotency_key="d1")
        donor_name = job.replica
        donor = a if donor_name == "r0" else b
        survivor = b if donor is a else a
        enter_drain_window(donor)
        front.check_replicas()
        assert job.frontier_handoff is True
        assert job.replica != donor_name
        assert survivor.engine.queue.get(job.remote_id) is not None
        assert front.frontier_handoffs == 1
        # the handoff runs ONCE per drain
        front.check_replicas()
        assert front.frontier_handoffs == 1
    finally:
        front.close()
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# fleet-shared store
# ---------------------------------------------------------------------------
def test_fleet_store_hit_from_replica_that_never_saw_the_contract(
    tmp_path,
):
    """Replica A computed (banked) the verdict; the front routes the
    repeat to replica B over the SAME store directory — B answers
    instantly from the shared store although it never analyzed the
    contract."""
    store = tmp_path / "store"
    a = replica_server(tmp_path, store=store)
    b = replica_server(tmp_path, store=store)
    bank(a, WRITER, issues=[{"title": "fleet-shared"}])
    front = FleetFront(FleetConfig([b.url], **FLEET_KW)).start()
    try:
        job, _ = front.submit_ex(WRITER, idempotency_key="shared")
        doc = front.report(job.id, wait_s=5.0)
        assert doc["state"] == "done"
        assert doc["report"]["store_hit"] is True
        assert doc["report"]["issues"] == [{"title": "fleet-shared"}]
        assert b.engine.vstore.hits >= 1
    finally:
        front.close()
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# Retry-After (satellite): server emits, client honors
# ---------------------------------------------------------------------------
def test_refusals_carry_retry_after(tmp_path):
    a = AnalysisServer(
        ServiceConfig(**dict(CFG, queue_capacity=1)), start_engine=False
    ).start()
    try:
        client = ServiceClient(a.url, retries=0, honor_retry_after=False)
        client.submit(KILLABLE)
        with pytest.raises(ServiceError) as full:
            client.submit(WRITER)
        assert full.value.status == 429
        assert full.value.retry_after == 1.0
        a.engine.queue.draining = True
        with pytest.raises(ServiceError) as draining:
            client.submit(BRANCHER)
        assert draining.value.status == 503
        assert draining.value.retry_after == 5.0
    finally:
        a.close()


def test_healthz_ready_503_carries_retry_after(tmp_path):
    a = replica_server(tmp_path)
    try:
        enter_drain_window(a)
        with pytest.raises(ServiceError) as refused:
            ServiceClient(a.url, retries=0).healthz(ready=True)
        assert refused.value.status == 503
        assert refused.value.retry_after == 5.0
        assert refused.value.payload.get("ready") is False
    finally:
        a.close()


def test_client_honors_retry_after_hint():
    """A 503 with Retry-After is retried after the server's hint
    (capped), not surfaced — the fixed-exponential path is only the
    fallback for hintless errors."""
    import http.server

    hits = []

    class Flaky(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            hits.append(time.monotonic())
            if len(hits) == 1:
                body = b'{"error":"busy"}'
                self.send_response(503)
                self.send_header("Retry-After", "0.05")
            else:
                body = b'{"ok":true}'
                self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    stub = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Flaky)
    threading.Thread(target=stub.serve_forever, daemon=True).start()
    try:
        client = ServiceClient(
            f"http://127.0.0.1:{stub.server_address[1]}", retries=2
        )
        assert client._request("/healthz") == {"ok": True}
        assert len(hits) == 2
        assert hits[1] - hits[0] >= 0.05
        # honoring OFF: the refusal surfaces immediately, hint attached
        hits.clear()
        strict = ServiceClient(
            f"http://127.0.0.1:{stub.server_address[1]}",
            retries=2, honor_retry_after=False,
        )
        with pytest.raises(ServiceError) as refused:
            strict._request("/healthz")
        assert refused.value.retry_after == 0.05
        assert len(hits) == 1
    finally:
        stub.shutdown()
        stub.server_close()


# ---------------------------------------------------------------------------
# the fleet HTTP face
# ---------------------------------------------------------------------------
def test_fleet_http_submit_report_stats_healthz(tmp_path):
    store = tmp_path / "store"
    a = replica_server(tmp_path, store=store)
    bank(a, KILLABLE)
    fleet = FleetServer(FleetConfig([a.url], **FLEET_KW)).start()
    try:
        client = ServiceClient(fleet.url)
        payload = client.submit_ex(KILLABLE, idempotency_key="http1")
        assert payload["replica"] == "r0"
        doc = client.report(payload["job_id"], wait_s=5.0)
        assert doc["state"] == "done"
        assert doc["report"]["issues"]
        # idempotent resubmit over HTTP says deduped
        again = client.submit_ex(KILLABLE, idempotency_key="http1")
        assert again["job_id"] == payload["job_id"]
        assert again.get("deduped") is True
        stats = client.stats()
        assert stats["fleet"]["submitted"] == 1
        assert stats["replicas"][0]["name"] == "r0"
        health = client.healthz()
        assert health["fleet"] is True and health["ready"] is True
        # unknown job -> 404
        with pytest.raises(ServiceError) as missing:
            client.job("0" * 12)
        assert missing.value.status == 404
        # /metrics exposes the fleet series
        text = urllib.request.urlopen(fleet.url + "/metrics").read(
        ).decode()
        assert "mtpu_fleet_submissions_total" in text
        assert "mtpu_fleet_replica_up" in text
    finally:
        fleet.close()
        a.close()


def test_fleet_http_shed_is_503_with_retry_after(tmp_path):
    a = replica_server(tmp_path)
    fleet = FleetServer(
        FleetConfig([a.url], retry_after_s=3, **FLEET_KW)
    ).start()
    try:
        enter_drain_window(a)
        fleet.front.check_replicas()
        client = ServiceClient(fleet.url, retries=0,
                               honor_retry_after=False)
        with pytest.raises(ServiceError) as shed:
            client.submit(KILLABLE)
        assert shed.value.status == 503
        assert shed.value.payload.get("reason") == "saturated"
        assert shed.value.retry_after == 3.0
        with pytest.raises(ServiceError) as probe:
            client.healthz(ready=True)
        assert probe.value.status == 503
        assert probe.value.retry_after == 3.0
    finally:
        fleet.close()
        a.close()


def test_front_never_routes_to_a_503_replica(tmp_path):
    """The acceptance wording, pinned directly: a replica whose
    /healthz?ready=1 answers 503 receives ZERO submissions while a
    200 replica exists."""
    a = replica_server(tmp_path)
    b = replica_server(tmp_path)
    front = FleetFront(FleetConfig([a.url, b.url], **FLEET_KW)).start()
    try:
        enter_drain_window(b)  # r1 probes 503 from here on
        front.check_replicas()
        before = b.engine.queue.accepted
        for i in range(6):
            job, _ = front.submit_ex(KILLABLE, idempotency_key=f"n{i}")
            assert job.replica == "r0"
        assert b.engine.queue.accepted == before
    finally:
        front.close()
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# front journal + recovery
# ---------------------------------------------------------------------------
def test_front_journal_recovery_reattaches_jobs(tmp_path):
    a = replica_server(tmp_path)
    journal_dir = str(tmp_path / "fleet-journal")
    front = FleetFront(
        FleetConfig([a.url], journal_dir=journal_dir, **FLEET_KW)
    ).start()
    job, _ = front.submit_ex(KILLABLE, idempotency_key="rec1")
    remote_id = job.remote_id
    front.close()  # clean shutdown; the journal holds the assignment
    try:
        front2 = FleetFront(
            FleetConfig(
                [a.url], journal_dir=journal_dir, recover=True,
                **FLEET_KW,
            )
        ).start()
        try:
            recovered = front2.get(job.id)
            assert recovered is not None and recovered.recovered
            assert recovered.replica == "r0"
            assert recovered.remote_id == remote_id
            assert recovered.idempotency_key == "rec1"
            # the idempotency index recovered too
            again, deduped = front2.submit_ex(
                KILLABLE, idempotency_key="rec1"
            )
            assert deduped and again.id == job.id
            # live status still flows from the replica
            assert front2.job_doc(job.id)["state"] == "queued"
        finally:
            front2.close()
    finally:
        a.close()


# ---------------------------------------------------------------------------
# operator view: myth observe top over multiple targets
# ---------------------------------------------------------------------------
def test_render_top_multi_columns_and_down_rows():
    from mythril_tpu.observe.opstool import render_top_multi

    stats = {
        "health": {"state": "ok", "ready": True},
        "queue": {"depth": 2, "capacity": 8, "jobs": {"done": 3}},
        "arena": {"lanes": 8, "lanes_busy": 4},
        "waves": {"count": 7},
        "store": {"answered": 5},
    }
    fleet_stats = {
        "health": {
            "state": "degraded",
            "ready": True,
            "reasons": ["replica-lost:r1", "fleet-degraded"],
        },
        "fleet": {
            "submitted": 9, "shed": 1, "failovers": 1,
            "rerouted": 2, "reroute_deduped": 2,
            "frontier_handoffs": 0,
        },
    }
    out = render_top_multi([
        ("http://127.0.0.1:7341", stats, None),
        ("http://127.0.0.1:7342", None, None),
        ("http://127.0.0.1:7340", fleet_stats, None),
    ])
    lines = out.splitlines()
    assert lines[0].startswith("target")
    assert any("2/8" in line and "4/8" in line for line in lines)
    assert any("DOWN" in line for line in lines)
    assert any("replica-lost:r1" in line for line in lines)
    assert any("reroute-deduped=2" in line for line in lines)


@pytest.mark.slow  # subprocess CLI = a full jax import; tox -e fleet
def test_observe_top_multi_url_cli(tmp_path):
    """`myth observe top --url A --url B --count 1 --json` renders one
    frame with a per-target payload and exits 0."""
    import subprocess
    import sys

    a = replica_server(tmp_path)
    try:
        proc = subprocess.run(
            [
                sys.executable, "-m", "mythril_tpu", "observe", "top",
                "--url", a.url,
                "--url", "http://127.0.0.1:1",  # unreachable: DOWN row
                "--count", "1", "--json",
            ],
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr
        frame = json.loads(proc.stdout.strip().splitlines()[-1])
        assert a.url in frame["targets"]
        assert frame["targets"][a.url]["queue"]["capacity"] == 8
        assert frame["targets"]["http://127.0.0.1:1"] is None
    finally:
        a.close()


# ---------------------------------------------------------------------------
# vocabulary pins
# ---------------------------------------------------------------------------
def test_fleet_redline_vocabulary_registered():
    from mythril_tpu.observe import slo

    assert slo.REDLINE_REPLICA_LOST in slo.REDLINE_REASONS
    assert slo.REDLINE_FLEET_DEGRADED in slo.REDLINE_REASONS
    assert slo.REDLINE_FLEET_SATURATED in slo.REDLINE_REASONS


def test_fleet_job_validates_code_like_the_service():
    with pytest.raises(ValueError):
        FleetJob("zz-not-hex")
    with pytest.raises(ValueError):
        FleetJob("")
    job = FleetJob("0x33ff")
    assert job.code_hex == "33ff" and job.code_len == 2
