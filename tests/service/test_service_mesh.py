"""The service's device-group mesh (`myth serve --devices N`): the
arena splits into per-group stripe blocks, admission stripes jobs over
the groups, each group gets its own dispatch/harvest pair, idle groups
steal resident jobs, and /stats surfaces the mesh counters."""

import pytest

from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
from mythril_tpu.service.jobs import Job
from mythril_tpu.service.lane_allocator import LaneAllocator

pytestmark = [pytest.mark.service, pytest.mark.multichip]

WRITER = "6001600055600060015500"
BRANCHER = "600035600757005b600160005500"
KILLABLE = "33ff"


# -- allocator group semantics ----------------------------------------------
def test_allocator_stripes_jobs_over_groups():
    alloc = LaneAllocator(stripes=4, lanes_per_stripe=4, groups=2)
    a = alloc.allocate("a")
    b = alloc.allocate("b")
    # least-loaded striping: the two jobs land in different groups
    assert alloc.group_of(a[0]) != alloc.group_of(b[0])
    occ = alloc.occupancy()
    assert [g["jobs_resident"] for g in occ["groups"]] == [1, 1]


def test_allocator_keeps_a_job_inside_one_group():
    alloc = LaneAllocator(stripes=4, lanes_per_stripe=4, groups=2)
    granted = alloc.allocate("wide", n_stripes=2)
    assert len({alloc.group_of(s) for s in granted}) == 1
    # a request bigger than one group's block is a config error
    with pytest.raises(ValueError):
        alloc.allocate("huge", n_stripes=3)


def test_allocator_pinned_group_grant():
    alloc = LaneAllocator(stripes=4, lanes_per_stripe=4, groups=2)
    granted = alloc.allocate("pinned", group=1)
    assert alloc.group_of(granted[0]) == 1
    assert alloc.jobs_in_group(1) == ["pinned"]
    assert alloc.jobs_in_group(0) == []


def test_allocator_rejects_indivisible_mesh():
    with pytest.raises(ValueError):
        LaneAllocator(stripes=3, lanes_per_stripe=4, groups=2)


# -- engine mesh dispatch ----------------------------------------------------
def test_mesh_engine_runs_one_dispatch_pair_per_group():
    """Two jobs on a 2-group engine: they stripe into distinct groups,
    each group's dispatch/harvest pair runs its own wave, and both
    reports carry harvested device results."""
    engine = AnalysisEngine(
        ServiceConfig(
            stripes=2, lanes_per_stripe=4, steps_per_wave=64, max_waves=2,
            host_walk=False, coalesce_wait_s=0.05, idle_wait_s=0.02,
            pipeline=True, devices=2,
        )
    ).start()
    try:
        jobs = [engine.submit(Job(WRITER)), engine.submit(Job(BRANCHER))]
        for job in jobs:
            settled = engine.queue.wait_terminal(job.id, timeout_s=180.0)
            assert settled is not None and settled.state == "done", (
                settled.state if settled else "lost"
            )
        stats = engine.stats()
        mesh = stats["mesh"]
        # groups = the requested split; devices = the ACTUAL device
        # count behind it (8 on the simulated test mesh)
        assert mesh["groups"] == 2
        assert mesh["devices"] == len(__import__("jax").devices())
        # one dispatch/harvest pair per group actually ran
        waves_per_group = [g["waves"] for g in mesh["per_device"]]
        assert all(w >= 1 for w in waves_per_group)
        # per-device occupancy is reported (stripes per group, busy)
        assert all(
            g["stripes"] == 1 and "stripes_busy" in g
            for g in mesh["per_device"]
        )
        # the branchy job's wave coverage harvested correctly through
        # the per-group readback assembly
        by_code = {j.code_hex if hasattr(j, "code_hex") else None for j in jobs}
        reports = [j.report["device"] for j in jobs]
        assert any(r["covered_branches"] >= 2 for r in reports)
        assert all(r["waves"] >= 1 for r in reports)
    finally:
        engine.close()


def test_mesh_engine_rebalances_job_to_idle_group():
    """The live balance: with both resident jobs in group 0 and group
    1 idle, the wave-boundary rebalance migrates one job across (steal
    + rebalance bytes counted), preserving its corpus/coverage."""
    engine = AnalysisEngine(
        ServiceConfig(
            stripes=4, lanes_per_stripe=4, steps_per_wave=64,
            host_walk=False, devices=2,
        )
    )
    # engine NOT started: drive admission by hand for determinism
    from mythril_tpu.service.engine import _JobTrack

    jobs = [Job(WRITER), Job(BRANCHER)]
    for job in jobs:
        engine.queue.submit(job)
    for job in engine.queue.claim(2):
        granted = engine.alloc.allocate(job.id, 1, group=0)  # crowd g0
        lanes = [l for s in granted for l in engine.alloc.lanes_of(s)]
        track = _JobTrack(job, granted, lanes, engine.cfg.calldata_len)
        engine._install_code(track)
        engine._tracks[job.id] = track
    assert engine.alloc.occupancy()["groups"][0]["jobs_resident"] == 2
    engine._rebalance()
    occ = engine.alloc.occupancy()["groups"]
    assert [g["jobs_resident"] for g in occ] == [1, 1]
    assert engine.mesh_steals == 1
    assert engine.mesh_rebalance_bytes > 0
    moved = next(
        t for t in engine._tracks.values()
        if engine.alloc.group_of(t.stripes[0]) == 1
    )
    # the migrated track's lanes and code row moved with it
    assert set(moved.lanes) <= set(engine.alloc.group_lanes(1))
    assert moved.code_row == moved.stripes[0]


def test_mesh_stats_present_on_single_device_engine():
    """Schema stability: the mesh block exists (trivially) without
    --devices, so /stats consumers never branch on its absence."""
    engine = AnalysisEngine(
        ServiceConfig(stripes=2, lanes_per_stripe=4, host_walk=False)
    )
    mesh = engine.stats()["mesh"]
    assert mesh["devices"] == 1 and mesh["groups"] == 1
    assert mesh["steals"] == 0
    assert len(mesh["per_device"]) == 1
