"""Pack-warmed service boot ordering (ISSUE 17, satellite 2).

The readiness contract `myth serve --kernel-pack DIR` pins:

- the pack is mounted SYNCHRONOUSLY in engine __init__, before the
  health monitor exists and before a server could bind;
- a pack that covers the engine's generic warmup executable clears
  `arena-warming` readiness immediately — no in-process compile clock;
- `--no-arena-warmup` + `--kernel-pack` compose: ready at once, pack
  still mounted and serving AOT executables to the first real wave;
- without a pack, `arena_warmup=True` leaves readiness pending until
  the warmup thread actually compiles;
- a cache dir alone configures the plane but mounts nothing;
- every mode degrades, never crashes: a bad pack dir boots a plain
  engine.

Engines here are constructed but never started — the contract under
test is boot state, and construction alone must establish it.
"""

import pytest

from mythril_tpu.compileplane.pack import bake_service_pack
from mythril_tpu.compileplane.plane import active_plane, reset_plane
from mythril_tpu.laser.batch import specialize as _spec
from mythril_tpu.laser.batch.run import clear_aot_generic, generic_aot_stats
from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
from mythril_tpu.support import breaker as cb

pytestmark = pytest.mark.compileplane

#: tiny dispatch shape shared by the bake and every engine below —
#: digests must match or the pack cannot cover the warmup
SHAPE = dict(stripes=2, lanes_per_stripe=2, steps_per_wave=32, code_cap=32)

CFG = dict(
    stripes=SHAPE["stripes"],
    lanes_per_stripe=SHAPE["lanes_per_stripe"],
    steps_per_wave=SHAPE["steps_per_wave"],
    code_cap=SHAPE["code_cap"],
    host_walk=False,
    pipeline=False,
    specialize=False,
    blockjit=False,
    store=False,
    breakers=False,
)


@pytest.fixture(scope="module")
def baked_pack(tmp_path_factory):
    pack_dir = str(tmp_path_factory.mktemp("bootpack") / "pack")
    reset_plane()
    clear_aot_generic()
    manifest = bake_service_pack(pack_dir, [None], **SHAPE)
    reset_plane()
    assert manifest["artifacts"] >= 1
    return pack_dir


@pytest.fixture(autouse=True)
def _clean_plane():
    reset_plane()
    clear_aot_generic()
    _spec.clear_kernel_cache()
    cb.reset_all()
    yield
    reset_plane()
    clear_aot_generic()
    _spec.clear_kernel_cache()
    cb.reset_all()


def test_pack_boot_is_ready_before_any_compile(baked_pack):
    engine = AnalysisEngine(
        ServiceConfig(**dict(CFG, arena_warmup=True, kernel_pack=baked_pack))
    )
    # mounted in __init__, before anything could have compiled
    assert engine._pack_mounted["mounted"] >= 1
    assert engine._pack_mounted["refused"] == 0
    assert engine._pack_covers_warmup()
    # readiness clears at construction: mounting WAS the warmup
    assert engine._warm_done.is_set()
    assert generic_aot_stats()["compiles"] == 0


def test_pack_warmup_wave_runs_zero_compiles(baked_pack):
    engine = AnalysisEngine(
        ServiceConfig(**dict(CFG, arena_warmup=True, kernel_pack=baked_pack))
    )
    engine._arena_warmup()  # the wave the warmup thread would run
    assert generic_aot_stats()["compiles"] == 0
    plane = active_plane()
    assert plane is not None and plane.pack_hits >= 1


def test_no_arena_warmup_composes_with_pack(baked_pack):
    engine = AnalysisEngine(
        ServiceConfig(**dict(CFG, arena_warmup=False, kernel_pack=baked_pack))
    )
    assert engine._warm_done.is_set()
    # the pack is not just decorative: still mounted, still consulted
    assert engine._pack_mounted["mounted"] >= 1
    assert active_plane() is not None


def test_without_pack_warmup_stays_pending():
    engine = AnalysisEngine(ServiceConfig(**dict(CFG, arena_warmup=True)))
    # no pack, warmup requested: readiness must wait for the compile
    assert not engine._warm_done.is_set()
    assert engine._pack_mounted == {}


def test_cache_dir_alone_configures_plane_without_mount(tmp_path):
    engine = AnalysisEngine(
        ServiceConfig(
            **dict(CFG, arena_warmup=False, kernel_cache_dir=str(tmp_path))
        )
    )
    plane = active_plane()
    assert plane is not None and plane.cache is not None
    assert engine._pack_mounted == {}
    assert engine._warm_done.is_set()


def test_bad_pack_dir_degrades_to_plain_boot(tmp_path):
    bogus = str(tmp_path / "not-a-pack")
    engine = AnalysisEngine(
        ServiceConfig(**dict(CFG, arena_warmup=False, kernel_pack=bogus))
    )
    # nothing mounted, nothing broken: the replica still serves
    assert engine._pack_mounted.get("mounted", 0) == 0
    assert engine._warm_done.is_set()


def test_kernel_stats_surface_pack_state(baked_pack):
    engine = AnalysisEngine(
        ServiceConfig(**dict(CFG, arena_warmup=True, kernel_pack=baked_pack))
    )
    stats = engine._kernel_stats()
    plane_stats = stats["compileplane"]
    assert plane_stats["pack_mount"]["mounted"] >= 1
    assert "kernel_pack_hit_rate" in plane_stats
    assert "aot_load_p50_s" in plane_stats
    assert stats["generic_aot"]["compiles"] == 0
