"""Per-job journey tracing through the service tier ladder (ISSUE 12,
tier-1 `service` + `observe` markers).

Pins that the three settle paths produce the correct DISTINCT tier
sequences at /v1/jobs/<id>/trace:

    store-hit      admission -> store-hit -> settle
    static-answer  admission -> static-answer -> settle
    full wave      admission -> queued -> lane-grant -> wave -> settle

and that the journey_id round-trips through the routing JSONL (schema
v3), so features ⨝ route ⨝ outcome ⨝ timeline joins offline. The two
admission-tier paths run on engine-less servers (the wave thread does
not exist — settling there PROVES the tier); the full path runs a
real engine. CPU-only."""

from __future__ import annotations

import json

import pytest

from mythril_tpu import observe
from mythril_tpu.analysis.corpusgen import clean_contract
from mythril_tpu.analysis.static import analysis_config_fingerprint
from mythril_tpu.service.client import ServiceClient
from mythril_tpu.service.engine import ServiceConfig
from mythril_tpu.service.server import AnalysisServer
from mythril_tpu.store import close_stores, code_hash_hex, open_store
from mythril_tpu.support.support_args import args as support_args

pytestmark = [pytest.mark.service, pytest.mark.observe]

#: CALLER; SELFDESTRUCT — never banked, never statically answerable
KILLABLE = "33ff"
#: tiny branching writer for the full wave path
WRITER = "6001600055600160015560026000f3"

CFG = dict(
    stripes=2,
    lanes_per_stripe=4,
    steps_per_wave=32,
    max_waves=1,
    queue_capacity=4,
    host_walk=False,
    coalesce_wait_s=0.02,
    idle_wait_s=0.02,
)

ISSUES = [{"address": 1, "swc-id": "110", "title": "banked",
           "contract": "b", "function": "f", "description": "d",
           "severity": "Medium", "min_gas_used": 0, "max_gas_used": 1,
           "sourceMap": None, "tx_sequence": None}]


def trace_of(client: ServiceClient, job_id: str) -> dict:
    return client._request(f"/v1/jobs/{job_id}/trace")


def routing_tail_for(journey_id: str) -> dict:
    for rec in observe.routing_log().tail(64):
        if rec.get("journey_id") == journey_id:
            return rec
    raise AssertionError(
        f"no routing record carries journey_id {journey_id}"
    )


def test_store_hit_journey(tmp_path):
    directory = str(tmp_path / "vstore")
    cfg = ServiceConfig(**CFG)
    open_store(directory).put(
        code_hash_hex(KILLABLE),
        analysis_config_fingerprint(
            transaction_count=cfg.transaction_count,
            create_timeout=cfg.create_timeout,
        ),
        issues=ISSUES,
        provenance={"computed_by": "seeder", "wall_s": 1.0},
    )
    srv = AnalysisServer(
        ServiceConfig(store_dir=directory, **CFG), start_engine=False
    ).start()
    try:
        client = ServiceClient(srv.url)
        job_id = client.submit(KILLABLE)
        job = client.job(job_id)
        assert job["state"] == "done"
        assert job["report"]["journey_id"] == job_id
        doc = trace_of(client, job_id)
        assert doc["journey_id"] == job_id
        assert doc["tiers"] == ["admission", "store-hit", "settle"]
        assert doc["schema_version"] == 1
        assert doc["state"] == "done"
        # the JSONL join key: the service emitted a routing record
        # (v4 since the cross-contract linker added link_* features)
        rec = routing_tail_for(job_id)
        assert rec["schema_version"] == 4
        assert rec["outcome"]["route"] == "store-hit"
    finally:
        srv.close()
        close_stores()


def test_static_answer_journey():
    previous = support_args.static_answer
    support_args.static_answer = True  # the conftest turns it off
    srv = AnalysisServer(
        ServiceConfig(**CFG), start_engine=False
    ).start()
    try:
        client = ServiceClient(srv.url)
        job_id = client.submit(clean_contract(0))
        assert client.job(job_id)["state"] == "done"
        doc = trace_of(client, job_id)
        assert doc["tiers"] == ["admission", "static-answer", "settle"]
        rec = routing_tail_for(job_id)
        assert rec["outcome"]["route"] == "static-answer"
        # the timeline join works offline too: the jsonl line parses
        # back with the same key
        parsed = observe.parse_routing_record(
            json.dumps(rec, sort_keys=True)
        )
        assert parsed["journey_id"] == job_id
        assert observe.assemble_journey(parsed["journey_id"])[
            "tiers"
        ] == doc["tiers"]
    finally:
        srv.close()
        support_args.static_answer = previous


def test_full_wave_journey_and_jsonl_roundtrip(tmp_path):
    observe.configure(out_dir=str(tmp_path))
    srv = AnalysisServer(ServiceConfig(**CFG)).start()
    try:
        client = ServiceClient(srv.url)
        job_id = client.submit(WRITER)
        report = client.report(job_id, wait_s=120.0)
        assert report["state"] == "done", report
        doc = trace_of(client, job_id)
        tiers = doc["tiers"]
        assert tiers[0] == "admission" and tiers[-1] == "settle"
        assert "queued" in tiers and "lane-grant" in tiers
        assert "wave" in tiers
        # the store/static tiers must NOT appear on the full path
        assert "store-hit" not in tiers
        assert "static-answer" not in tiers
        # per-tier dwell covers every tier touched
        assert set(doc["tier_dwell_s"]) == set(tiers)
        # wave events carry their wave index
        waves = [e for e in doc["events"] if e["tier"] == "wave"]
        assert any(e["event"] == "dispatch" for e in waves)
        assert any(e["event"] == "harvest" for e in waves)
        # journey_id rides the on-disk routing JSONL (schema v3)
        path = tmp_path / "routing_features.jsonl"
        assert path.exists()
        records = observe.read_routing_records(str(path))
        match = [r for r in records if r["journey_id"] == job_id]
        assert match, f"no JSONL record for journey {job_id}"
        assert match[0]["outcome"]["route"] in (
            "device-owned", "host-walk"
        )
    finally:
        srv.close()
        observe.configure(out_dir=None)


def test_trace_unknown_job_is_404():
    srv = AnalysisServer(
        ServiceConfig(**CFG), start_engine=False
    ).start()
    try:
        client = ServiceClient(srv.url)
        from mythril_tpu.service.client import ServiceError

        with pytest.raises(ServiceError) as refusal:
            trace_of(client, "0" * 12)
        assert refusal.value.status == 404
    finally:
        srv.close()


def test_healthz_readiness_split_and_draining_reason():
    srv = AnalysisServer(ServiceConfig(**CFG), start_engine=False).start()
    try:
        # honoring OFF: the ready-probe 503 below carries Retry-After
        # (ISSUE 15); the default client would retry-sleep through it
        client = ServiceClient(srv.url, honor_retry_after=False)
        health = client.healthz()
        assert health["ok"] is True
        assert health["state"] in ("ok", "degraded")
        assert health["ready"] is True
        assert health["not_ready_reasons"] == []
        assert isinstance(health["objectives"], list)
        srv.engine.drain()
        health = client.healthz()
        assert health["draining"] is True
        assert health["ready"] is False
        assert "draining" in health["not_ready_reasons"]
        # the readiness PROBE flips to 503 while the payload stays
        from mythril_tpu.service.client import ServiceError

        with pytest.raises(ServiceError) as refusal:
            client._request("/healthz?ready=1")
        assert refusal.value.status == 503
        assert refusal.value.payload["not_ready_reasons"]
    finally:
        srv.close()
