"""Crash-safe serving suite (`-m chaos`): the durable job journal,
journal recovery with verdict-store dedupe, poison-job quarantine,
and the tier circuit breakers.

Engine-less servers wherever the machinery under test lives at
admission (journal WAL ordering, recovery re-admission, quarantine
denylist, idempotency dedupe); small started engines where a real
wave fault is the subject (strike escalation, the device-tier breaker
ladder). The subprocess SIGKILL-mid-wave harness — the half that
needs a process to actually die — is tools/chaos_smoke.py, wired as
tox [testenv:chaos]. CPU-only.
"""

from __future__ import annotations

import hashlib
import os
import time

import pytest

from mythril_tpu.analysis.corpusgen import poison_contract
from mythril_tpu.exceptions import InjectedFault
from mythril_tpu.service.client import ServiceClient
from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
from mythril_tpu.service.jobs import Job, JobState
from mythril_tpu.service.journal import (
    JobJournal,
    replay_dir,
)
from mythril_tpu.service.server import AnalysisServer
from mythril_tpu.store import open_store
from mythril_tpu.support import breaker as cb
from mythril_tpu.support.resilience import (
    DegradationLog,
    DegradationReason,
    arm_fault,
    disarm_faults,
)
from mythril_tpu.support.support_args import args as support_args

pytestmark = [pytest.mark.chaos, pytest.mark.service]

#: the fault-suite shapes (tests/laser/test_pipeline.py)
KILLABLE = "33ff"
WRITER = "6001600055600060015500"
BRANCHER = "600035600757005b600160005500"

CFG = dict(
    stripes=2,
    lanes_per_stripe=4,
    steps_per_wave=64,
    max_waves=1,
    queue_capacity=8,
    host_walk=False,
    coalesce_wait_s=0.02,
    idle_wait_s=0.02,
)


def code_hash(code_hex: str) -> str:
    return hashlib.sha256(bytes.fromhex(code_hex)).hexdigest()


@pytest.fixture(autouse=True)
def _clean_slate():
    """Breakers and armed faults are process-global: every test gets
    a fresh board and leaves none armed."""
    cb.reset_all()
    disarm_faults()
    yield
    cb.reset_all()
    disarm_faults()


def _engine(tmp_path, **overrides) -> AnalysisEngine:
    cfg = dict(CFG)
    cfg.update(overrides)
    return AnalysisEngine(ServiceConfig(**cfg))


def _wait_terminal(engine, job_id, timeout_s=60.0):
    job = engine.queue.wait_terminal(job_id, timeout_s)
    assert job is not None and job.terminal, (
        f"job {job_id} not terminal: {job and job.state}"
    )
    return job


# -- 1. journal append/replay round-trip ------------------------------------


def test_journal_append_replay_roundtrip(tmp_path):
    jd = str(tmp_path / "wal")
    journal = JobJournal(jd)
    job = Job(KILLABLE, max_waves=3, idempotency_key="key-1")
    assert journal.job_admitted(job)
    assert journal.jobs_claimed([job.id])
    assert journal.wave_dispatched([job.id])
    done = Job(WRITER, idempotency_key="key-2")
    assert journal.job_admitted(done)
    assert journal.job_settled(done, JobState.DONE)
    journal.close()

    replay = replay_dir(jd)
    assert replay.records == 5
    assert not replay.clean_shutdown  # no drain marker: a crash
    inflight = replay.jobs[job.id]
    assert inflight.code_hex == KILLABLE
    assert inflight.params["max_waves"] == 3
    assert inflight.idempotency_key == "key-1"
    assert inflight.inflight and not inflight.terminal
    settled = replay.jobs[done.id]
    assert settled.terminal and settled.state == JobState.DONE
    assert settled.code_hash == code_hash(WRITER)
    assert [inflight] == replay.crash_implicated()

    # a drain marker flips the crash classification
    journal2 = JobJournal(jd)
    journal2.mark_drain()
    journal2.close()
    replay = replay_dir(jd)
    assert replay.clean_shutdown
    assert replay.crash_implicated() == []


def test_journal_replay_tolerates_torn_tail(tmp_path):
    jd = str(tmp_path / "wal")
    journal = JobJournal(jd)
    job = Job(KILLABLE)
    journal.job_admitted(job)
    journal.close()
    # the crash landed mid-append: a torn half-record at the tail
    with open(journal.path, "a") as fp:
        fp.write('{"event": "settl')
    replay = replay_dir(jd)
    assert replay.torn_lines == 1
    assert replay.records == 1  # the good record still replays
    assert job.id in replay.jobs


# -- 2. recovery re-admission + store dedupe --------------------------------


def test_recovery_readmits_and_dedupes_through_store(tmp_path):
    jd = str(tmp_path / "wal")
    sd = str(tmp_path / "store")
    cfg = dict(CFG, journal_dir=jd, store_dir=sd)
    engine = AnalysisEngine(ServiceConfig(**cfg))  # never started
    job = Job(KILLABLE, idempotency_key="idem-r1")
    engine.submit(job)
    assert job.state == JobState.QUEUED and job.journaled_admit
    # bank the verdict the re-run would compute (the PR-11 store is
    # what recovery dedupes through)
    open_store(sd).put(
        code_hash(KILLABLE), engine._config_fp,
        issues=[{"title": "banked"}],
    )
    del engine  # the process "dies" (no drain marker was written)

    recovered = AnalysisEngine(
        ServiceConfig(**dict(cfg, recover=True))
    )
    back = recovered.queue.get(job.id)
    assert back is not None, "acknowledged job lost across the crash"
    assert back.recovered and back.state == JobState.DONE
    assert back.report["store_hit"] is True
    assert back.report["issues"] == [{"title": "banked"}]
    stats = recovered.stats()
    assert stats["journal"]["recovered_jobs"] == 1
    assert stats["journal"]["recovery_deduped"] == 1
    # the idempotency index survived the restart
    retry = recovered.submit(Job(KILLABLE, idempotency_key="idem-r1"))
    assert retry.id == job.id
    # prior segments compacted into the fresh one
    assert len(
        [n for n in os.listdir(jd) if n.startswith("wal-")]
    ) == 1


def test_recovery_adopts_terminal_jobs_as_history(tmp_path):
    jd = str(tmp_path / "wal")
    cfg = dict(CFG, journal_dir=jd)
    engine = AnalysisEngine(ServiceConfig(**cfg))
    job = Job(KILLABLE)
    engine.submit(job)
    engine.queue.settle(job, JobState.DONE)
    del engine

    recovered = AnalysisEngine(ServiceConfig(**dict(cfg, recover=True)))
    back = recovered.queue.get(job.id)
    assert back is not None and back.state == JobState.DONE
    assert back.recovered
    # nothing re-ran: the job was already terminal in the journal
    assert recovered.stats()["journal"]["recovered_jobs"] == 0


# -- 3. crash implication + quarantine --------------------------------------


def test_crash_implicated_job_quarantines_at_strike_threshold(tmp_path):
    """A job that was ON THE DEVICE when the process died takes a
    crash-implication strike at recovery; at the strike threshold the
    re-admission settles FAILED + QUARANTINED instead of crashing the
    same wave forever."""
    jd = str(tmp_path / "wal")
    journal = JobJournal(jd)
    job = Job(poison_contract(0))
    journal.job_admitted(job)
    journal.jobs_claimed([job.id])
    journal.wave_dispatched([job.id])
    journal.close()  # no drain marker: SIGKILL mid-wave

    engine = AnalysisEngine(ServiceConfig(**dict(
        CFG, journal_dir=jd, recover=True, quarantine_strikes=1,
    )))
    back = engine.queue.get(job.id)
    assert back is not None and back.state == JobState.FAILED
    assert DegradationReason.QUARANTINED in back.degraded
    assert back.report["quarantined"] is True
    stats = engine.stats()
    assert stats["quarantine"]["denylisted"] == 1
    assert stats["quarantine"]["quarantined"] == 1


def test_crash_implication_below_threshold_readmits_with_strike(tmp_path):
    jd = str(tmp_path / "wal")
    journal = JobJournal(jd)
    job = Job(poison_contract(1))
    journal.job_admitted(job)
    journal.wave_dispatched([job.id])
    journal.close()

    engine = AnalysisEngine(ServiceConfig(**dict(
        CFG, journal_dir=jd, recover=True, quarantine_strikes=2,
    )))
    back = engine.queue.get(job.id)
    assert back is not None and back.state == JobState.QUEUED
    assert engine._strikes[code_hash(poison_contract(1))] == 1
    assert engine._is_suspect(code_hash(poison_contract(1)))


def test_quarantine_strike_escalation_solo_then_failed(tmp_path):
    """The live escalation: wave fault -> strike 1 (FAILED, codehash
    now suspect) -> resubmission runs SOLO and faults again -> strike
    2 settles FAILED with QUARANTINED + denylists -> a third submit
    settles instantly at admission with no wave at all."""
    poison = poison_contract(2)
    engine = _engine(
        tmp_path, stripes=1, lanes_per_stripe=2, quarantine_strikes=2,
    ).start()
    try:
        # every device attempt faults while armed: the dispatch AND
        # the whole resilience ladder underneath it (one dispatch
        # fault per submission — the pipelined loop can dispatch a
        # second wave for the same job before the first harvest)
        arm_fault(
            "service.dispatch", times=1,
            exc=InjectedFault("device.dispatch.poisoned"),
        )
        arm_fault("device.dispatch", times=9999)
        first = engine.submit(Job(poison))
        job1 = _wait_terminal(engine, first.id)
        assert job1.state == JobState.FAILED
        assert DegradationReason.QUARANTINED not in job1.degraded
        assert engine._strikes[code_hash(poison)] == 1

        arm_fault(
            "service.dispatch", times=1,
            exc=InjectedFault("device.dispatch.poisoned"),
        )
        second = engine.submit(Job(poison))  # runs solo (suspect)
        job2 = _wait_terminal(engine, second.id)
        assert job2.state == JobState.FAILED
        assert DegradationReason.QUARANTINED in job2.degraded
        disarm_faults()

        waves_before = engine.waves_total
        third = engine.submit(Job(poison))
        # settled synchronously at admission: no wave ran for it
        assert third.state == JobState.FAILED
        assert DegradationReason.QUARANTINED in third.degraded
        assert engine.waves_total == waves_before
        stats = engine.stats()
        assert stats["quarantine"]["quarantined"] >= 2
        assert stats["quarantine"]["denylisted"] == 1
    finally:
        disarm_faults()
        engine.drain(timeout_s=30.0)


def test_suspect_job_is_isolated_to_a_solo_wave(tmp_path):
    """A striked codehash never shares the arena: submit a suspect and
    an innocent together; the arena must never hold both at once (the
    innocent still completes)."""
    poison = poison_contract(3)
    engine = _engine(tmp_path, stripes=2, lanes_per_stripe=2).start()
    try:
        engine._strike(code_hash(poison))  # suspect, below threshold
        suspect = engine.submit(Job(poison))
        innocent = engine.submit(Job(WRITER))
        _wait_terminal(engine, suspect.id)
        _wait_terminal(engine, innocent.id)
        assert innocent.state == JobState.DONE
        # with 2 stripes these two WOULD have shared a wave; the solo
        # gate kept residency at one job at a time
        assert engine.alloc.occupancy()["max_jobs_resident"] == 1
        # the suspect passed its solo wave: the strike cleared
        assert code_hash(poison) not in engine._strikes
    finally:
        engine.drain(timeout_s=30.0)


def test_quarantine_corpus_differential(tmp_path):
    """The acceptance differential: a corpus containing one
    repeat-crashing contract completes with every OTHER contract's
    issue-bearing outcome identical to a run without the poison, and
    the poison settles FAILED with QUARANTINED."""
    poison = poison_contract(4)
    innocents = [KILLABLE, WRITER, BRANCHER]

    def outcome(job):
        device = (job.report or {}).get("device") or {}
        return (
            device.get("covered_branches"),
            tuple(sorted((device.get("triggers") or {}).items())),
        )

    def run_corpus(with_poison: bool):
        engine = _engine(
            tmp_path, stripes=1, lanes_per_stripe=2,
            quarantine_strikes=2,
        ).start()
        results = {}
        poison_jobs = []
        try:
            order = (
                [poison] + innocents[:1] + [poison] + innocents[1:]
                if with_poison
                else list(innocents)
            )
            for code in order:
                if code == poison:
                    # the poison's waves fault while it is resident
                    # (sequential submission keeps the blast radius
                    # attribution unambiguous here; shared-wave
                    # attribution is the solo-isolation test's job)
                    arm_fault(
                        "service.dispatch", times=1,
                        exc=InjectedFault("device.dispatch.poison"),
                    )
                    arm_fault("device.dispatch", times=9999)
                job = engine.submit(Job(code))
                _wait_terminal(engine, job.id, timeout_s=120.0)
                if code == poison:
                    disarm_faults()
                    poison_jobs.append(job)
                else:
                    results[code] = outcome(job)
            return results, poison_jobs
        finally:
            disarm_faults()
            engine.drain(timeout_s=30.0)

    with_p, poison_jobs = run_corpus(with_poison=True)
    cb.reset_all()
    without_p, _ = run_corpus(with_poison=False)
    # every innocent's issue-bearing outcome is untouched by the
    # poison's presence
    assert with_p == without_p
    # and the poison escalated: second failure quarantined it
    assert [j.state for j in poison_jobs] == ["failed", "failed"]
    assert DegradationReason.QUARANTINED in poison_jobs[-1].degraded


# -- 4. tier circuit breakers ------------------------------------------------


def test_breaker_state_machine_transitions():
    clock = [0.0]
    br = cb.CircuitBreaker(
        "test-tier", failure_threshold=3, recovery_s=10.0,
        clock=lambda: clock[0],
    )
    assert br.allow() and br.state == cb.STATE_CLOSED
    br.record_failure()
    br.record_failure()
    assert br.state == cb.STATE_CLOSED  # below the threshold
    br.record_failure()
    assert br.state == cb.STATE_OPEN and not br.allow()
    assert br.trips == 1
    clock[0] = 9.0
    assert not br.allow()  # recovery clock still running
    clock[0] = 10.5
    assert br.allow() and br.state == cb.STATE_HALF_OPEN
    br.record_failure()  # the probe failed: re-open, re-arm
    assert br.state == cb.STATE_OPEN and br.trips == 2
    clock[0] = 21.0
    assert br.allow() and br.state == cb.STATE_HALF_OPEN
    br.record_success()  # healthy probe: closed, counters reset
    assert br.state == cb.STATE_CLOSED and br.allow()
    # a success resets the consecutive count
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == cb.STATE_CLOSED


def test_breaker_failure_rate_trips_without_consecutive_run():
    br = cb.CircuitBreaker(
        "rate-tier", failure_threshold=100, window=4,
        rate_threshold=0.5, recovery_s=10.0,
    )
    for _ in range(3):
        br.record_failure()
        br.record_success()
    # window [F,S,F,S] -> rate 0.5 >= threshold on a full window
    assert br.state == cb.STATE_OPEN


def test_device_breaker_open_serves_through_host_ladder(tmp_path):
    """The acceptance shape: with the device-dispatch breaker open the
    service KEEPS SERVING — jobs route straight down the ladder (zero
    waves) — and /healthz reports the enumerated breaker-open:device
    reason."""
    engine = _engine(tmp_path).start()
    try:
        cb.breaker(cb.TIER_DEVICE).force_open()
        job = engine.submit(Job(WRITER))
        done = _wait_terminal(engine, job.id)
        assert done.state == JobState.DONE
        assert "breaker-open:device" in done.degraded
        assert done.report["device"]["waves"] == 0  # never dispatched
        assert engine.waves_total == 0
        payload = engine.health.healthz_payload()
        assert payload["state"] == "redlined"
        assert "breaker-open:device" in payload["reasons"]
        assert payload["ready"] is False
        stats = engine.stats()
        assert stats["breaker"]["enabled"] is True
        assert stats["breaker"]["tiers"]["device"]["state"] == "open"
    finally:
        engine.drain(timeout_s=30.0)


def test_device_breaker_trips_on_wave_faults_and_recovers(tmp_path):
    """closed -> open on a real injected wave fault (threshold 1),
    then the half-open probe wave closes it again once the faults
    stop."""
    # the trip fires at the harvest fault, ~1s BEFORE the doomed
    # resilience ladder finishes — the recovery window must outlast
    # the ladder for the open state to be observable
    cb.configure(cb.TIER_DEVICE, failure_threshold=1, recovery_s=4.0)
    engine = _engine(tmp_path, stripes=1, lanes_per_stripe=2).start()
    try:
        arm_fault(
            "service.dispatch", times=1,
            exc=InjectedFault("device.dispatch.wedged"),
        )
        arm_fault("device.dispatch", times=9999)
        failed = engine.submit(Job(BRANCHER))
        _wait_terminal(engine, failed.id)
        assert failed.state == JobState.FAILED
        assert cb.breaker(cb.TIER_DEVICE).state == cb.STATE_OPEN
        assert cb.breaker(cb.TIER_DEVICE).trips == 1
        disarm_faults()

        # inside the recovery window jobs still settle via the ladder
        skipped = engine.submit(Job(WRITER))
        _wait_terminal(engine, skipped.id)
        assert skipped.state == JobState.DONE
        assert skipped.report["device"]["waves"] == 0  # routed around

        # past recovery_s: the next wave is a half-open probe
        deadline = time.monotonic() + 10.0
        while (
            cb.breaker(cb.TIER_DEVICE).state == cb.STATE_OPEN
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        probe = engine.submit(Job(WRITER))
        _wait_terminal(engine, probe.id)
        assert probe.state == JobState.DONE
        assert probe.report["device"]["waves"] >= 1
        assert cb.breaker(cb.TIER_DEVICE).state == cb.STATE_CLOSED
    finally:
        disarm_faults()
        engine.drain(timeout_s=30.0)


def test_kernel_breaker_open_forces_generic_waves(tmp_path):
    prev = support_args.specialize
    support_args.specialize = True  # the conftest turns it off
    engine = _engine(tmp_path, specialize=True).start()
    try:
        cb.breaker(cb.TIER_KERNEL).force_open()
        job = engine.submit(Job(WRITER))
        done = _wait_terminal(engine, job.id)
        assert done.state == JobState.DONE
        # every wave ran the generic interpreter: the specialized
        # tier was routed around (no compile paid), not retried
        assert engine.spec_waves == 0
        assert engine.generic_waves >= 1
    finally:
        support_args.specialize = prev
        engine.drain(timeout_s=30.0)


def test_store_breaker_open_degrades_to_miss(tmp_path):
    sd = str(tmp_path / "store")
    store = open_store(sd)
    assert store.put("a" * 64, "fp", issues=[]) is not None
    cb.breaker(cb.TIER_STORE).force_open()
    assert store.get("a" * 64, "fp") is None  # hit becomes a miss
    assert store.put("b" * 64, "fp", issues=[]) is None  # write no-op
    cb.reset_all()
    assert store.get("a" * 64, "fp") is not None  # the entry survived


def test_store_write_fault_feeds_breaker_and_degrades(tmp_path):
    sd = str(tmp_path / "faulty-store")
    store = open_store(sd)
    cb.configure(cb.TIER_STORE, failure_threshold=2, recovery_s=30.0)
    arm_fault("store.write", times=2)
    assert store.put("c" * 64, "fp", issues=[]) is None
    assert store.put("d" * 64, "fp", issues=[]) is None
    assert cb.breaker(cb.TIER_STORE).state == cb.STATE_OPEN
    disarm_faults()
    # open breaker: writes stay no-ops without touching the disk
    assert store.put("e" * 64, "fp", issues=[]) is None


def test_breaker_open_device_solve_matches_host_first_funnel():
    """Ladder-fallback parity: an OPEN device-solve breaker must
    produce the same issue-bearing outcomes as --host-first-funnel —
    the breaker routes down the same ladder the flag selects."""
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

    def fingerprint(contract):
        return (
            tuple(map(tuple, contract["covered_branches"])),
            {
                kind: tuple(sorted(t["pc"] for t in bucket))
                for kind, bucket in contract["triggers"].items()
            },
        )

    codes = [KILLABLE, WRITER, BRANCHER]
    kw = dict(
        lanes_per_contract=8, waves=3, steps_per_wave=64,
        transaction_count=1, seed=7,
    )
    prev = support_args.device_first
    try:
        support_args.device_first = True
        cb.breaker(cb.TIER_DEVICE_SOLVE).force_open()
        ex_open = DeviceCorpusExplorer(codes, **kw)
        run_open = ex_open.run()
        # the open breaker kept the device solver out entirely
        assert ex_open.stats.device_sat + ex_open.stats.device_unsat == 0
        cb.reset_all()

        support_args.device_first = False
        ex_host = DeviceCorpusExplorer(codes, **kw)
        run_host = ex_host.run()
    finally:
        support_args.device_first = prev
    for a, b in zip(run_open["contracts"], run_host["contracts"]):
        assert fingerprint(a) == fingerprint(b)


def test_no_breakers_flag_disables_the_layer(tmp_path):
    prev = support_args.breakers
    support_args.breakers = False
    try:
        cb.breaker(cb.TIER_DEVICE).force_open()
        assert cb.allow(cb.TIER_DEVICE)  # the switch wins
        assert cb.open_reasons() == [] or not cb.breakers_enabled()
        engine = _engine(tmp_path)
        assert engine.stats()["breaker"]["enabled"] is False
    finally:
        support_args.breakers = prev


# -- 5. journal fault degradation -------------------------------------------


def test_journal_write_fault_degrades_to_nondurable(tmp_path):
    jd = str(tmp_path / "wal")
    engine = AnalysisEngine(
        ServiceConfig(**dict(CFG, journal_dir=jd))
    )  # never started
    marker = DegradationLog().marker()
    arm_fault("service.journal.write", times=1)
    job = engine.submit(Job(KILLABLE))
    # admission SUCCEEDED despite the dead journal...
    assert job.state == JobState.QUEUED
    assert job.journaled_admit is False
    # ...and the loss of durability is recorded, not hidden
    assert engine.journal.degraded is True
    counts = DegradationLog().counts_since(marker)
    assert counts.get(DegradationReason.JOURNAL_DEGRADED) == 1
    stats = engine.stats()
    assert stats["journal"]["degraded"] is True
    assert stats["journal"]["errors"] == 1


# -- 6. idempotency ----------------------------------------------------------


def test_idempotent_resubmit_over_http(tmp_path):
    server = AnalysisServer(
        ServiceConfig(**CFG), start_engine=False
    ).start()
    try:
        client = ServiceClient(server.url)
        job_id = client.submit(KILLABLE, idempotency_key="same-key")
        again = client.submit(KILLABLE, idempotency_key="same-key")
        assert again == job_id
        # distinct keys are distinct jobs
        other = client.submit(KILLABLE, idempotency_key="other-key")
        assert other != job_id
        assert client.stats()["queue"]["depth"] == 2
    finally:
        server.close()


def test_client_retries_connection_refused():
    """The client retries refused connections with backoff instead of
    failing the first attempt (a restarting server looks exactly like
    this); after the retries it surfaces the real error."""
    client = ServiceClient(
        "http://127.0.0.1:1", retries=2, backoff_s=0.01,
    )
    t0 = time.monotonic()
    with pytest.raises(Exception) as excinfo:
        client.stats()
    assert time.monotonic() - t0 >= 0.02  # both backoffs slept
    assert not isinstance(excinfo.value, AssertionError)
