"""Lane-stripe allocator: the packing logic under the service's
continuous batching (pure host-side bookkeeping, no device)."""

import pytest

from mythril_tpu.service.lane_allocator import LaneAllocator

pytestmark = pytest.mark.service


def test_allocate_release_roundtrip():
    alloc = LaneAllocator(stripes=4, lanes_per_stripe=8)
    a = alloc.allocate("job-a")
    b = alloc.allocate("job-b", n_stripes=2)
    assert len(a) == 1 and len(b) == 2
    assert set(a).isdisjoint(b)
    assert alloc.occupancy()["stripes_busy"] == 3
    assert alloc.owner_of(a[0]) == "job-a"
    alloc.release(a)
    assert alloc.occupancy()["stripes_busy"] == 2
    assert alloc.owner_of(a[0]) is None
    # the freed stripe is reusable immediately — mid-run, not at drain
    c = alloc.allocate("job-c", n_stripes=2)
    assert c is not None and set(c).isdisjoint(b)


def test_allocation_is_all_or_nothing():
    alloc = LaneAllocator(stripes=2, lanes_per_stripe=4)
    assert alloc.allocate("a") is not None
    # two stripes wanted, one free: refuse outright (a partial grant
    # would strand the job half-resident) and leave the free list alone
    assert alloc.allocate("b", n_stripes=2) is None
    assert alloc.occupancy()["stripes_busy"] == 1
    assert alloc.allocate("c") is not None


def test_oversized_request_is_an_error_not_a_wait():
    alloc = LaneAllocator(stripes=2, lanes_per_stripe=4)
    with pytest.raises(ValueError):
        alloc.allocate("huge", n_stripes=3)


def test_lane_math_and_stripes_needed():
    alloc = LaneAllocator(stripes=3, lanes_per_stripe=8)
    assert alloc.n_lanes == 24
    assert alloc.lanes_of(1) == list(range(8, 16))
    assert alloc.stripes_needed(1) == 1
    assert alloc.stripes_needed(8) == 1
    assert alloc.stripes_needed(9) == 2
    assert alloc.stripes_needed(16) == 2


def test_high_water_marks_track_coalescing():
    alloc = LaneAllocator(stripes=4, lanes_per_stripe=8)
    a = alloc.allocate("a")
    b = alloc.allocate("b")
    alloc.release(a)
    alloc.release(b)
    occ = alloc.occupancy()
    # the /stats proof that two jobs shared the arena at once
    assert occ["max_jobs_resident"] == 2
    assert occ["max_lanes_busy"] == 16
    assert occ["jobs_resident"] == 0


def test_invalid_arena_shape_rejected():
    with pytest.raises(ValueError):
        LaneAllocator(stripes=0, lanes_per_stripe=8)
