"""Job model + bounded admission queue: the service's backpressure
contract (429 on full, 503 on draining) without any device work."""

import pytest

from mythril_tpu.service.jobs import Job, JobQueue, JobState, QueueRefusal

pytestmark = pytest.mark.service


def test_job_normalizes_and_validates_code():
    job = Job("0x33ff")
    assert job.code == bytes.fromhex("33ff")
    assert job.state == JobState.QUEUED
    with pytest.raises(ValueError):
        Job("0xzz")
    with pytest.raises(ValueError):
        Job("")


def test_fifo_claim_and_unclaim():
    queue = JobQueue(capacity=4)
    first, second = Job("33ff"), Job("6001")
    queue.submit(first)
    queue.submit(second)
    claimed = queue.claim(1)
    assert claimed == [first] and first.state == JobState.RUNNING
    # the arena couldn't fit it: back to the queue HEAD, still FIFO
    queue.unclaim(first)
    assert first.state == JobState.QUEUED
    assert queue.claim(2) == [first, second]


def test_full_queue_refuses_with_backpressure_reason():
    queue = JobQueue(capacity=1)
    queue.submit(Job("33ff"))
    with pytest.raises(QueueRefusal) as refusal:
        queue.submit(Job("6001"))
    assert refusal.value.reason == "full"  # -> HTTP 429
    assert queue.rejected_full == 1


def test_draining_queue_refuses_and_hands_back_pending():
    queue = JobQueue(capacity=4)
    job = Job("33ff")
    queue.submit(job)
    remaining = queue.drain_remaining()
    assert remaining == [job]
    assert queue.depth() == 0
    with pytest.raises(QueueRefusal) as refusal:
        queue.submit(Job("6001"))
    assert refusal.value.reason == "draining"  # -> HTTP 503


def test_wait_terminal_long_poll():
    queue = JobQueue()
    job = Job("33ff")
    queue.submit(job)
    # not terminal yet: the wait times out and returns the live job
    assert queue.wait_terminal(job.id, 0.05) is job
    assert not job.terminal
    queue.settle(job, JobState.DONE)
    settled = queue.wait_terminal(job.id, 0.05)
    assert settled.terminal and settled.state == JobState.DONE
    assert queue.wait_terminal("0" * 12, 0.01) is None


def test_job_dict_shape():
    job = Job("33ff", deadline_s=30.0)
    out = job.as_dict()
    assert out["state"] == "queued"
    assert out["code_len"] == 2
    assert "report" not in out
    job.report = {"issues": []}
    assert Job("33ff").deadline is None
    assert job.as_dict()["report"] == {"issues": []}
