"""The static-answer triage tier at service admission: a submission
the semantic screen proves clean settles DONE before it ever reaches
the queue — no wave dispatch, no arena lane, no host walk.

Engine-less servers throughout (start_engine=False): the triage path
runs on the HTTP thread inside `AnalysisEngine.submit`, so a job that
completes here PROVABLY never saw a device dispatch — the wave thread
does not exist. CPU-only, sub-second.
"""

from __future__ import annotations

import pytest

from mythril_tpu.analysis.corpusgen import clean_contract
from mythril_tpu.service.client import ServiceClient, ServiceError
from mythril_tpu.service.engine import ServiceConfig
from mythril_tpu.service.server import AnalysisServer
from mythril_tpu.support.support_args import args as support_args

pytestmark = [pytest.mark.service, pytest.mark.taint]

#: CALLER; SELFDESTRUCT — never statically answerable
KILLABLE = "33ff"

CFG = dict(
    stripes=2,
    lanes_per_stripe=4,
    steps_per_wave=64,
    queue_capacity=4,
    host_walk=False,
)


@pytest.fixture()
def triage_enabled():
    previous = support_args.static_answer
    support_args.static_answer = True  # the conftest turns it off
    yield
    support_args.static_answer = previous


@pytest.fixture()
def server(triage_enabled):
    srv = AnalysisServer(
        ServiceConfig(**CFG), start_engine=False
    ).start()
    yield srv
    srv.close()


def test_clean_submission_settles_without_device_dispatch(server):
    client = ServiceClient(server.url, honor_retry_after=False)
    job_id = client.submit(clean_contract(0))
    job = client.job(job_id)
    # already terminal: no wave thread even exists on this server
    assert job["state"] == "done"
    report = job["report"]
    assert report["static_answered"] is True
    assert report["issues"] == []
    assert "device" not in report  # no wave block — none ever ran
    assert report["static"]["modules_applicable"] == 0
    stats = client.stats()
    assert stats["static"]["static_answered"] == 1
    assert stats["static"]["answer_enabled"] is True
    assert stats["waves"]["count"] == 0
    assert stats["queue"]["jobs"].get("done") == 1


def test_unanswerable_submission_queues_normally(server):
    client = ServiceClient(server.url, honor_retry_after=False)
    job_id = client.submit(KILLABLE)
    job = client.job(job_id)
    assert job["state"] == "queued"  # engine-less: stays queued
    assert client.stats()["static"]["static_answered"] == 0


def test_triage_skips_full_queue_backpressure(server):
    """Answered jobs never occupy a queue slot, so they keep settling
    even when the pending queue is FULL — triage is admission
    capacity, not arena capacity."""
    client = ServiceClient(server.url, honor_retry_after=False)
    for _ in range(CFG["queue_capacity"]):
        client.submit(KILLABLE)
    with pytest.raises(ServiceError):
        client.submit(KILLABLE)  # 429: the queue is full
    job_id = client.submit(clean_contract(1))
    assert client.job(job_id)["state"] == "done"


def test_config_knob_disables_triage(triage_enabled):
    srv = AnalysisServer(
        ServiceConfig(**dict(CFG, static_answer=False)),
        start_engine=False,
    ).start()
    try:
        client = ServiceClient(srv.url, honor_retry_after=False)
        job_id = client.submit(clean_contract(0))
        assert client.job(job_id)["state"] == "queued"
        stats = client.stats()
        assert stats["static"]["static_answered"] == 0
        assert stats["static"]["answer_enabled"] is False
    finally:
        srv.close()


def test_args_flag_disables_triage(server):
    """--no-static-prune parity: with the process-wide static layer
    off, the triage tier must not fire regardless of the service
    config."""
    client = ServiceClient(server.url, honor_retry_after=False)
    previous = support_args.static_prune
    support_args.static_prune = False
    try:
        job_id = client.submit(clean_contract(2))
        assert client.job(job_id)["state"] == "queued"
    finally:
        support_args.static_prune = previous


def test_draining_refuses_triaged_submissions(triage_enabled):
    srv = AnalysisServer(
        ServiceConfig(**CFG), start_engine=False
    ).start()
    client = ServiceClient(srv.url, honor_retry_after=False)
    srv.engine.drain(timeout_s=5.0)
    with pytest.raises(ServiceError):
        client.submit(clean_contract(0))  # 503: draining
