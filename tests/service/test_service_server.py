"""End-to-end service tests: a real AnalysisServer on 127.0.0.1, a
real HTTP client, CPU JAX.

One module-scoped server (one fixed arena shape) so the whole suite
pays at most one kernel compile; the drain/backpressure tests use
engine-less servers (start_engine=False) that never dispatch a wave.
CPU-only and sized to stay well under a minute warm."""

import threading

import numpy as np
import pytest

from mythril_tpu.laser.batch.checkpoint import (
    checkpoint_shape,
    load_checkpoint,
)
from mythril_tpu.service.client import ServiceClient, ServiceError
from mythril_tpu.service.engine import ServiceConfig
from mythril_tpu.service.server import AnalysisServer

pytestmark = pytest.mark.service

#: PUSH1 1 PUSH1 0 SSTORE PUSH1 0 PUSH1 1 SSTORE STOP
WRITER = "6001600055600060015500"
#: CALLER SELFDESTRUCT — banks a selfdestruct trigger in one wave
KILLABLE = "33ff"
#: CALLDATALOAD(0) branches to a storage write — one coverable JUMPI
BRANCHER = "600035600757005b600160005500"

CFG = dict(
    stripes=2,
    lanes_per_stripe=4,
    steps_per_wave=64,
    max_waves=2,
    queue_capacity=8,
    host_walk=False,  # device-only by default; one test opts in
    execution_timeout=5,
    coalesce_wait_s=0.15,
    idle_wait_s=0.02,
)


@pytest.fixture(scope="module")
def server():
    srv = AnalysisServer(ServiceConfig(**CFG)).start()
    yield srv
    srv.close()


@pytest.fixture(scope="module")
def client(server):
    return ServiceClient(server.url)


def test_healthz_and_stats_shape(server, client):
    health = client.healthz()
    assert health["ok"] is True and health["draining"] is False
    stats = client.stats()
    assert stats["queue"]["capacity"] == 8
    assert stats["arena"]["lanes"] == 8
    assert {"count", "rate_per_s", "steps_per_wave"} <= set(stats["waves"])
    assert "degradation" in stats


def test_concurrent_jobs_coalesce_into_shared_waves(server, client):
    """Two concurrent submissions must share waves (lane occupancy > 1
    contract) and both reports must arrive — the continuous-batching
    acceptance signal."""
    ids = []
    submit = lambda code: ids.append(client.submit(code))  # noqa: E731
    threads = [
        threading.Thread(target=submit, args=(code,))
        for code in (WRITER, BRANCHER)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(ids) == 2
    reports = [client.report(job_id, wait_s=90.0) for job_id in ids]
    for job in reports:
        assert job["state"] == "done", job
        assert job["report"]["device"]["waves"] == 2
        assert job["report"]["device"]["lane_steps"] > 0
    stats = client.stats()
    assert stats["arena"]["max_jobs_resident"] >= 2
    assert stats["waves"]["count"] >= 2
    # the branching contract's waves covered at least one direction
    # (identified by code hash — the racing submit threads may append
    # ids in either order)
    import hashlib

    brancher_hash = hashlib.sha256(
        bytes.fromhex(BRANCHER)
    ).hexdigest()
    brancher = next(
        job["report"]
        for job in reports
        if job["report"]["code_hash"] == brancher_hash
    )
    assert brancher["device"]["covered_branches"] >= 1


def test_trigger_witness_reaches_the_report(server, client):
    job_id = client.submit(KILLABLE)
    job = client.report(job_id, wait_s=90.0)
    assert job["state"] == "done"
    assert job["report"]["device"]["triggers"].get("selfdestruct", 0) >= 1


def test_code_cache_warms_on_resubmission(server, client):
    before = client.stats()["warm"]["code_cache"]["hits"]
    job_id = client.submit(KILLABLE)  # same hash as the previous test
    assert client.report(job_id, wait_s=90.0)["state"] == "done"
    assert client.stats()["warm"]["code_cache"]["hits"] > before


def test_per_job_deadline_degrades_not_crashes(server, client):
    """An already-expired per-request deadline: the job still completes
    (device phase bounded at the wave boundary) with the degradation
    recorded — resource exhaustion is an outcome, not a crash."""
    job_id = client.submit(WRITER, deadline_s=0.0)
    job = client.report(job_id, wait_s=90.0)
    assert job["state"] == "done"
    assert "deadline-expired" in job["report"].get("degraded", [])
    assert job["report"]["device"]["waves"] == 1  # cut at the boundary


def test_host_walk_overlaps_and_reports_issues(server, client):
    """One job opts into the host walk: the device outcome is injected
    into the pooled-mode worker and the report carries host results."""
    job_id = client.submit(KILLABLE, host_walk=True)
    job = client.report(job_id, wait_s=120.0)
    assert job["state"] == "done", job
    assert "host" in job["report"]
    assert job["report"]["host"]["error"] is None
    assert isinstance(job["report"]["issues"], list)


def test_bad_requests_are_400_not_500(server, client):
    with pytest.raises(ServiceError) as bad:
        client.submit("0xzz")
    assert bad.value.status == 400
    with pytest.raises(ServiceError) as missing:
        client.job("f" * 12)
    assert missing.value.status == 404


def test_queue_full_answers_429():
    srv = AnalysisServer(
        ServiceConfig(**dict(CFG, queue_capacity=1)), start_engine=False
    ).start()
    try:
        # honoring OFF: the default client would retry the 429 after
        # the server's Retry-After hint (ISSUE 15) and book one
        # rejection per attempt — this test pins the single-refusal
        # accounting, the honoring behavior is pinned in tests/fleet
        client = ServiceClient(srv.url, honor_retry_after=False)
        client.submit(WRITER)
        with pytest.raises(ServiceError) as refusal:
            client.submit(KILLABLE)
        assert refusal.value.status == 429
        assert refusal.value.retry_after == 1.0
        assert client.stats()["queue"]["rejected_full"] == 1
    finally:
        srv.close()


def test_drain_checkpoints_every_accepted_job(tmp_path):
    """The SIGTERM contract: accepted-but-unfinished jobs end up
    CHECKPOINTED with a replayable npz (correct shape metadata), and a
    draining server answers 503."""
    srv = AnalysisServer(
        ServiceConfig(**dict(CFG, checkpoint_dir=str(tmp_path))),
        start_engine=False,  # jobs stay queued: the pure drain path
    ).start()
    # honoring OFF: the 503 below carries Retry-After (ISSUE 15) and
    # the default client would sleep through three futile retries
    client = ServiceClient(srv.url, honor_retry_after=False)
    ids = [client.submit(WRITER), client.submit(BRANCHER)]
    srv.engine.drain()
    try:
        for job_id in ids:
            job = client.job(job_id)
            assert job["state"] == "checkpointed", job
            path = job["checkpoint"]
            # the npz is a real, replayable frontier: it loads, carries
            # its code table, and its shape metadata says what arena
            # wrote it
            batch, code, step = load_checkpoint(path)
            assert code is not None and step == CFG["steps_per_wave"]
            shape = checkpoint_shape(path)
            assert shape["lanes"] == CFG["lanes_per_stripe"]
            assert shape["code_rows"] == 1
            assert int(np.asarray(batch.calldatasize).max()) > 0  # seeded
            # a mismatched arena refuses it instead of resharding junk
            with pytest.raises(ValueError, match="arena shape"):
                load_checkpoint(path, expect_shape={"lanes": 512})
        with pytest.raises(ServiceError) as refusal:
            client.submit(KILLABLE)
        assert refusal.value.status == 503
        assert client.healthz()["draining"] is True
    finally:
        srv.close()


def test_drain_is_idempotent_and_close_safe():
    srv = AnalysisServer(ServiceConfig(**CFG), start_engine=False).start()
    srv.engine.drain()
    srv.engine.drain()  # second drain returns immediately
    srv.close()
    srv.close()  # close after drain is a no-op
