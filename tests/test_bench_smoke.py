"""bench.py emission contract: the parsed one-line JSON record prints
HEADLINE-FIRST (inside the budget, rc 0) and carries the mesh fields —
the capture-window guarantee BENCH_r05 lacked (rc:124/parsed:null),
pinned at toy scale via the MYTHRIL_BENCH_* env knobs."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parsed_lines(stdout: str):
    out = []
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except ValueError:
                pass
    return out


def test_bench_emits_headline_record_inside_budget(tmp_path):
    """A tiny-budget bench run must exit 0 within the window and print
    at least one complete parseable record (corpus phases report
    budget-skipped rather than eating the wall), with the mesh fields
    present — plus the ISSUE-8 flight-recorder fields: the loss
    waterfall balances the run's cdcl-sat count exactly, and
    MYTHRIL_BENCH_CAPTURE_DIR leaves a replayable corpus behind."""
    capture_dir = str(tmp_path / "qcorpus")
    env = dict(
        os.environ,
        MYTHRIL_BENCH_BUDGET_S="70",
        MYTHRIL_BENCH_HEADLINE_S="50",
        MYTHRIL_BENCH_LANES="256",
        MYTHRIL_BENCH_STEPS="64",
        MYTHRIL_BENCH_CONTRACTS="2",
        MYTHRIL_BENCH_PAIRS="0",  # toy run: headline phases only
        MYTHRIL_BENCH_CAPTURE_DIR=capture_dir,
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = _parsed_lines(proc.stdout)
    assert records, f"no parseable JSON line in: {proc.stdout!r}"
    # incremental emission: the headline line printed BEFORE the final
    stages = [r.get("bench_emit") for r in records]
    assert stages[0] == "headline"
    assert stages[-1] == "final"
    final = records[-1]
    # schema-complete even with the corpus half disabled
    for field in (
        "metric", "value", "unit", "vs_baseline", "bench_wall_s",
        "mesh_devices", "steal_count", "static_prune_rate",
        "solver_loss_reasons", "captured_queries", "cdcl_sat_verdicts",
    ):
        assert field in final, f"missing {field}"
    assert final["corpus"] == "disabled"
    assert final["bench_wall_s"] <= 70 + 45  # the budget held
    # the flight-recorder accounting identity (ISSUE 8 acceptance):
    # every host-won query carries exactly one loss reason
    assert sum(final["solver_loss_reasons"].values()) == (
        final["cdcl_sat_verdicts"]
    ), final["solver_loss_reasons"]
    # the capture corpus landed beside the record (dedup can fold
    # repeat queries into fewer files than captures; a budget-starved
    # toy run that solved nothing leaves an armed-but-empty dir)
    assert final.get("capture_dir") == capture_dir
    artifacts = [
        name
        for name in os.listdir(capture_dir)
        if name.startswith("q-") and name.endswith(".json")
    ]
    assert (len(artifacts) > 0) == (final["captured_queries"] > 0)
    assert len(artifacts) <= max(1, final["captured_queries"])


@pytest.mark.slow
def test_bench_headline_pair_reports_mesh_occupancy():
    """With one real (toy) convergence pair, the record reports the
    per-device occupancy + steal counters from the mesh prepass —
    slow tier: two real analyze_corpus legs."""
    env = dict(
        os.environ,
        MYTHRIL_BENCH_BUDGET_S="600",
        MYTHRIL_BENCH_HEADLINE_S="540",
        MYTHRIL_BENCH_LANES="256",
        MYTHRIL_BENCH_STEPS="64",
        MYTHRIL_BENCH_CONTRACTS="4",
        MYTHRIL_BENCH_PAIRS="1",
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=700,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    final = _parsed_lines(proc.stdout)[-1]
    assert final.get("corpus_pairs") == 1
    assert "mesh_occupancy" in final
    assert isinstance(final["steal_count"], int)
    assert final["mesh_devices"] >= 1
