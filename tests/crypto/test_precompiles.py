"""Precompile vectors (reference test strategy: tests/laser/Precompiles/)."""

import hashlib

import pytest

from mythril_tpu.crypto import bn128
from mythril_tpu.laser.ethereum import natives


def as_words(*ints):
    out = []
    for v in ints:
        out += list(v.to_bytes(32, "big"))
    return out


def test_ecrecover_known_vector():
    # the canonical CallEcrecover vector from the Ethereum test suite
    h = bytes.fromhex(
        "456e9aea5e197a1f1af7a3e85a3212fa4049a3ba34c2289b4c860fc0b0c64ef3"
    )
    v = 28
    r = int("9242685bf161793cc25603c231bc2f568eb630ea16aa137d2664ac8038825608", 16)
    s = int("4f8ae3bd7535248d0bd448298cc2e2071e56992d0774dc340c368ae950852ada", 16)
    data = list(h) + as_words(v, r, s)
    out = natives.ecrecover(data)
    assert bytes(out[12:]).hex() == "7156526fbd7a3c72969b54f64e42c10fbb768c8a"
    assert out[:12] == [0] * 12


def test_ecrecover_invalid_v_returns_empty():
    assert natives.ecrecover([0] * 32 + as_words(26, 1, 1)) == []


def test_sha256_matches_hashlib():
    data = list(b"hello world")
    assert bytes(natives.sha256(data)) == hashlib.sha256(b"hello world").digest()


def test_ripemd160_padded_to_32():
    out = natives.ripemd160(list(b"abc"))
    assert len(out) == 32
    assert out[:12] == [0] * 12
    assert (
        bytes(out[12:]).hex() == "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"
    )


def test_identity():
    assert natives.identity([1, 2, 3]) == [1, 2, 3]


def test_mod_exp_simple():
    # 3^5 mod 7 = 5
    data = as_words(1, 1, 1) + [3, 5, 7]
    assert natives.mod_exp(data) == [5]


def test_mod_exp_zero_modulus():
    data = as_words(1, 1, 1) + [3, 5, 0]
    assert natives.mod_exp(data) == [0]


def test_ec_add_doubles_generator():
    data = as_words(1, 2, 1, 2)
    out = natives.ec_add(data)
    x = int.from_bytes(bytes(out[:32]), "big")
    y = int.from_bytes(bytes(out[32:]), "big")
    expected = bn128.double(bn128.G1)
    assert (x, y) == (expected[0].n, expected[1].n)


def test_ec_add_identity():
    data = as_words(1, 2, 0, 0)
    out = natives.ec_add(data)
    assert int.from_bytes(bytes(out[:32]), "big") == 1
    assert int.from_bytes(bytes(out[32:]), "big") == 2


def test_ec_mul_matches_add():
    data = as_words(1, 2, 2)
    out = natives.ec_mul(data)
    doubled = natives.ec_add(as_words(1, 2, 1, 2))
    assert out == doubled


def test_ec_mul_invalid_point():
    assert natives.ec_mul(as_words(1, 3, 2)) == []


def test_ec_pair_empty_input_is_one():
    assert natives.ec_pair([]) == [0] * 31 + [1]


def test_ec_pair_bilinear():
    # e(G1, G2) * e(-G1, G2) == 1
    g2 = (
        bn128.G2[0].coeffs,
        bn128.G2[1].coeffs,
    )
    neg_g1_y = bn128.field_modulus - 2
    pairs = as_words(
        1, 2, g2[0][1], g2[0][0], g2[1][1], g2[1][0],
        1, neg_g1_y, g2[0][1], g2[0][0], g2[1][1], g2[1][0],
    )
    assert natives.ec_pair(pairs) == [0] * 31 + [1]


def test_ec_pair_bad_length():
    assert natives.ec_pair([0] * 100) == []


def test_blake2b_eip152_vector():
    # EIP-152 test vector 5: F(blake2b-IV-with-params, "abc", t=3, final)
    rounds = (12).to_bytes(4, "big")
    h = bytes.fromhex(
        "48c9bdf267e6096a3ba7ca8485ae67bb2bf894fe72f36e3cf1361d5f3af54fa5"
        "d182e6ad7f520e511f6c3e2b8c68059b6bbd41fbabd9831f79217e1319cde05b"
    )
    m = b"abc" + b"\x00" * 125
    t = (3).to_bytes(8, "little") + (0).to_bytes(8, "little")
    raw = rounds + h + m + t + b"\x01"
    assert len(raw) == 213
    out = natives.blake2b_fcompress(list(raw))
    assert bytes(out).hex() == (
        "ba80a53f981c4d0d6a2797b69f12f6e94c212f14685ac4b74b12bb6fdbffa2d1"
        "7d87c5392aab792dc252d5de4533cc9518d38aa8dbf1925ab92386edd4009923"
    )


def test_blake2b_bad_length():
    assert natives.blake2b_fcompress([0] * 100) == []
