"""Targeted per-instruction regression tests.

Mirrors the reference's focused suite layout
(/root/reference/tests/instructions/: create2_test, create_test,
extcodehash_test, extcodecopy_test, codecopy_test, sar/shl/shr_test,
static_call_test) for the post-Constantinople opcodes the vendored
VMTests generation predates — these semantics otherwise ride on fewer
direct assertions than the reference keeps.

Shift vectors are the canonical EIP-145 spec examples; the CREATE2
address check recomputes EIP-1014 independently of the handler.
"""

import pytest

from mythril_tpu.laser.ethereum.evm_exceptions import WriteProtection
from mythril_tpu.laser.ethereum.transaction.transaction_models import (
    ContractCreationTransaction,
    TransactionStartSignal,
)
from mythril_tpu.support.support_utils import get_code_hash, keccak256
from mythril_tpu.laser.smt import symbol_factory

from tests.instructions.test_instruction_semantics import (
    bv,
    make_state,
    run_op,
)

MAX = 2**256 - 1
NEG1 = MAX  # two's-complement -1


def run_signal(state, op):
    """Evaluate an op that must open a nested frame; return the
    signal."""
    from mythril_tpu.laser.ethereum.instructions import Instruction

    with pytest.raises(TransactionStartSignal) as excinfo:
        Instruction(op, None).evaluate(state)
    return excinfo.value


def _write_memory(state, at, data: bytes):
    state.mstate.mem_extend(at, len(data))
    for i, b in enumerate(data):
        state.mstate.memory[at + i] = b


# ---------------------------------------------------------------------------
# EIP-145 shift vectors (spec examples, verbatim)
# ---------------------------------------------------------------------------
SHL_VECTORS = [
    (0x01, 0x00, 0x01),
    (0x01, 0x01, 0x02),
    (0x01, 0xFF, 1 << 255),
    (0x01, 0x100, 0x00),
    (0x01, 0x101, 0x00),
    (MAX, 0x00, MAX),
    (MAX, 0x01, MAX - 1),
    (MAX, 0xFF, 1 << 255),
    (MAX, 0x100, 0x00),
    (0x00, 0x01, 0x00),
    (1 << 255, 0x01, 0x00),
]

SHR_VECTORS = [
    (0x01, 0x00, 0x01),
    (0x01, 0x01, 0x00),
    (1 << 255, 0x01, 1 << 254),
    (1 << 255, 0xFF, 0x01),
    (1 << 255, 0x100, 0x00),
    (1 << 255, 0x101, 0x00),
    (MAX, 0x00, MAX),
    (MAX, 0x01, MAX >> 1),
    (MAX, 0xFF, 0x01),
    (MAX, 0x100, 0x00),
    (0x00, 0x01, 0x00),
]

SAR_VECTORS = [
    (0x01, 0x00, 0x01),
    (0x01, 0x01, 0x00),
    (1 << 255, 0x01, 0b11 << 254),
    (1 << 255, 0xFF, NEG1),
    (1 << 255, 0x100, NEG1),
    (1 << 255, 0x101, NEG1),
    (NEG1, 0x00, NEG1),
    (NEG1, 0x01, NEG1),
    (NEG1, 0xFF, NEG1),
    (NEG1, 0x100, NEG1),
    (0x00, 0x01, 0x00),
    (0x4000000000000000000000000000000000000000000000000000000000000000, 0xFE, 0x01),
    (MAX >> 1, 0xF8, 0x7F),
    (MAX >> 1, 0xFE, 0x01),
    (MAX >> 1, 0xFF, 0x00),
    (MAX >> 1, 0x100, 0x00),
]


def _shift(op, value, shift):
    state = make_state()
    state.mstate.stack.append(bv(value))
    state.mstate.stack.append(bv(shift))
    return run_op(state, op).mstate.stack[-1].value


@pytest.mark.parametrize("value,shift,expected", SHL_VECTORS)
def test_shl_eip145(value, shift, expected):
    assert _shift("SHL", value, shift) == expected


@pytest.mark.parametrize("value,shift,expected", SHR_VECTORS)
def test_shr_eip145(value, shift, expected):
    assert _shift("SHR", value, shift) == expected


@pytest.mark.parametrize("value,shift,expected", SAR_VECTORS)
def test_sar_eip145(value, shift, expected):
    assert _shift("SAR", value, shift) == expected


# ---------------------------------------------------------------------------
# EXTCODEHASH (EIP-1052)
# ---------------------------------------------------------------------------
def test_extcodehash_missing_account_is_zero():
    state = make_state()
    state.mstate.stack.append(bv(0x1234567890))  # no such account
    assert run_op(state, "EXTCODEHASH").mstate.stack[-1].value == 0


def test_extcodehash_existing_account_hashes_code():
    state = make_state()
    # make_state creates account 101 with code 60006000
    state.mstate.stack.append(bv(101))
    out = run_op(state, "EXTCODEHASH").mstate.stack[-1].value
    assert out == int(get_code_hash("60006000"), 16)


def test_extcodehash_truncates_address_to_160_bits():
    state = make_state()
    # dirty upper bits must be ignored (address is the low 160 bits)
    state.mstate.stack.append(bv((0xDEAD << 160) | 101))
    out = run_op(state, "EXTCODEHASH").mstate.stack[-1].value
    assert out == int(get_code_hash("60006000"), 16)


# ---------------------------------------------------------------------------
# CODECOPY / EXTCODECOPY
# ---------------------------------------------------------------------------
def test_codecopy_copies_own_code_and_zero_pads():
    state = make_state(code_hex="60026000")
    # dest=0, code offset=2, length=4 (code is 4 bytes: pads 2 zeros)
    state.mstate.stack.append(bv(4))
    state.mstate.stack.append(bv(2))
    state.mstate.stack.append(bv(0))
    out = run_op(state, "CODECOPY")
    got = [out.mstate.memory[i] for i in range(4)]
    got = [g.value if hasattr(g, "value") else g for g in got]
    assert got == [0x60, 0x00, 0x00, 0x00]


def test_extcodecopy_reads_foreign_code():
    state = make_state()
    # copy account 101's 4-byte code to memory at 8
    state.mstate.stack.append(bv(4))  # length
    state.mstate.stack.append(bv(0))  # code offset
    state.mstate.stack.append(bv(8))  # dest
    state.mstate.stack.append(bv(101))  # address
    out = run_op(state, "EXTCODECOPY")
    got = [out.mstate.memory[8 + i] for i in range(4)]
    got = [g.value if hasattr(g, "value") else g for g in got]
    assert got == [0x60, 0x00, 0x60, 0x00]


# ---------------------------------------------------------------------------
# CREATE / CREATE2 (EIP-1014)
# ---------------------------------------------------------------------------
INIT_CODE = bytes.fromhex("600a600c600039600a6000f3")  # returns 10 bytes


def _push_create_args(state, value=0, at=0, length=len(INIT_CODE)):
    state.mstate.stack.append(bv(length))
    state.mstate.stack.append(bv(at))
    state.mstate.stack.append(bv(value))


def test_create_opens_creation_transaction():
    state = make_state()
    _write_memory(state, 0, INIT_CODE)
    _push_create_args(state, value=7)
    signal = run_signal(state, "CREATE")
    txn = signal.transaction
    assert isinstance(txn, ContractCreationTransaction)
    assert txn.code.bytecode == INIT_CODE.hex()
    assert txn.call_value.value == 7
    # plain CREATE: address assigned by the engine, not pinned here
    assert signal.op_code == "CREATE"


def test_create2_concrete_salt_pins_eip1014_address():
    state = make_state()
    _write_memory(state, 0, INIT_CODE)
    salt = 0x2A
    state.mstate.stack.append(bv(salt))
    _push_create_args(state)
    signal = run_signal(state, "CREATE2")
    txn = signal.transaction
    creator = 101  # make_state's account address
    preimage = (
        b"\xff"
        + creator.to_bytes(20, "big")
        + salt.to_bytes(32, "big")
        + keccak256(INIT_CODE)
    )
    expected = int.from_bytes(keccak256(preimage)[12:], "big")
    got = txn.callee_account.address
    got = got.value if hasattr(got, "value") else got
    assert got == expected


def test_create2_resume_pushes_created_address():
    from mythril_tpu.laser.ethereum.instructions import Instruction

    state = make_state()
    for v in (4, 3, 2, 1):  # the 4 original operands, re-popped on resume
        state.mstate.stack.append(bv(v))
    state.last_return_data = "0xbebebebebebebebebebebebebebebebebebebebe"
    out = Instruction("CREATE2", None).evaluate(state, post=True)[0]
    assert out.mstate.stack[-1].value == 0xBEBEBEBEBEBEBEBEBEBEBEBEBEBEBEBEBEBEBEBE


def test_create_resume_failed_creation_pushes_zero():
    from mythril_tpu.laser.ethereum.instructions import Instruction

    state = make_state()
    for v in (3, 2, 1):
        state.mstate.stack.append(bv(v))
    state.last_return_data = None
    out = Instruction("CREATE", None).evaluate(state, post=True)[0]
    assert out.mstate.stack[-1].value == 0


# ---------------------------------------------------------------------------
# WriteProtection inside STATICCALL context (reference:
# tests/instructions/static_call_test.py)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "op,operands",
    [
        ("SSTORE", 2),
        ("LOG0", 2),
        ("LOG1", 3),
        ("LOG2", 4),
        ("LOG3", 5),
        ("LOG4", 6),
        ("CREATE", 3),
        ("CREATE2", 4),
        ("SUICIDE", 1),  # 0xff's table mnemonic (SELFDESTRUCT alias)
    ],
)
def test_state_mutators_raise_write_protection_in_static_context(op, operands):
    from mythril_tpu.laser.ethereum.instructions import Instruction

    state = make_state(static=True)
    for i in range(operands):
        state.mstate.stack.append(bv(i))
    with pytest.raises(WriteProtection):
        Instruction(op, None).evaluate(state)


def test_call_with_value_raises_write_protection_in_static_context():
    from mythril_tpu.laser.ethereum.instructions import Instruction

    state = make_state(static=True)
    # gas, to, VALUE=1, in_at, in_len, out_at, out_len
    for v in (0, 0, 0, 0, 1, 101, 100):
        state.mstate.stack.append(bv(v))
    with pytest.raises(WriteProtection):
        Instruction("CALL", None).evaluate(state)
