"""Instruction-handler unit tests on hand-built GlobalStates
(reference test strategy: tests/instructions/)."""

import pytest

from mythril_tpu.disassembler.disassembly import Disassembly
from mythril_tpu.laser.ethereum.evm_exceptions import WriteProtection
from mythril_tpu.laser.ethereum.instructions import Instruction
from mythril_tpu.laser.ethereum.state.account import Account
from mythril_tpu.laser.ethereum.state.calldata import ConcreteCalldata
from mythril_tpu.laser.ethereum.state.environment import Environment
from mythril_tpu.laser.ethereum.state.global_state import GlobalState
from mythril_tpu.laser.ethereum.state.machine_state import MachineState
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.ethereum.transaction.transaction_models import (
    MessageCallTransaction,
)
from mythril_tpu.laser.smt import symbol_factory


def make_state(code_hex="60006000", static=False):
    world_state = WorldState()
    account = world_state.create_account(balance=10, address=101)
    account.code = Disassembly(code_hex)
    environment = Environment(
        account,
        symbol_factory.BitVecVal(0xABC, 256),
        ConcreteCalldata("1", []),
        symbol_factory.BitVecVal(1, 256),
        symbol_factory.BitVecVal(0, 256),
        symbol_factory.BitVecVal(0xABC, 256),
        static=static,
    )
    state = GlobalState(world_state, environment, None, MachineState(gas_limit=8000000))
    state.transaction_stack.append(
        (
            MessageCallTransaction(
                world_state=world_state,
                gas_limit=8000000,
                identifier="1",
                callee_account=account,
                caller=environment.sender,
                call_value=0,
            ),
            None,
        )
    )
    return state


def bv(v, w=256):
    return symbol_factory.BitVecVal(v, w)


def run_op(state, op):
    return Instruction(op, None).evaluate(state)[0]


def test_add_wraps():
    state = make_state()
    state.mstate.stack.append(bv(2**256 - 1))
    state.mstate.stack.append(bv(2))
    out = run_op(state, "ADD")
    assert out.mstate.stack[-1].value == 1


def test_sub_order():
    state = make_state()
    state.mstate.stack.append(bv(3))
    state.mstate.stack.append(bv(10))
    out = run_op(state, "SUB")
    assert out.mstate.stack[-1].value == 7


def test_div_by_zero():
    state = make_state()
    state.mstate.stack.append(bv(0))
    state.mstate.stack.append(bv(5))
    out = run_op(state, "DIV")
    assert out.mstate.stack[-1].value == 0


def test_sdiv_signed():
    state = make_state()
    state.mstate.stack.append(bv(2))
    state.mstate.stack.append(bv(2**256 - 4))  # -4
    out = run_op(state, "SDIV")
    assert out.mstate.stack[-1].value == 2**256 - 2  # -2


def test_byte_extracts():
    state = make_state()
    state.mstate.stack.append(bv(0xAABBCC))
    state.mstate.stack.append(bv(29))  # byte 29 (0-indexed from MSB)
    out = run_op(state, "BYTE")
    assert out.mstate.stack[-1].value == 0xAA


def test_byte_out_of_range():
    state = make_state()
    state.mstate.stack.append(bv(0xAABBCC))
    state.mstate.stack.append(bv(40))
    out = run_op(state, "BYTE")
    assert out.mstate.stack[-1].value == 0


def test_shl_shr_sar():
    state = make_state()
    state.mstate.stack.append(bv(1))
    state.mstate.stack.append(bv(4))
    assert run_op(state, "SHL").mstate.stack.pop().value == 16

    state.mstate.stack.append(bv(16))
    state.mstate.stack.append(bv(4))
    assert run_op(state, "SHR").mstate.stack.pop().value == 1

    state.mstate.stack.append(bv(2**256 - 16))  # -16
    state.mstate.stack.append(bv(2))
    assert run_op(state, "SAR").mstate.stack.pop().value == 2**256 - 4


def test_signextend():
    state = make_state()
    state.mstate.stack.append(bv(0xFF))
    state.mstate.stack.append(bv(0))
    out = run_op(state, "SIGNEXTEND")
    assert out.mstate.stack[-1].value == 2**256 - 1


def test_iszero():
    state = make_state()
    state.mstate.stack.append(bv(0))
    assert run_op(state, "ISZERO").mstate.stack.pop().value == 1
    state.mstate.stack.append(bv(7))
    assert run_op(state, "ISZERO").mstate.stack.pop().value == 0


def test_exp_concrete():
    state = make_state()
    state.mstate.stack.append(bv(10))  # exponent
    state.mstate.stack.append(bv(2))  # base
    out = run_op(state, "EXP")
    assert out.mstate.stack[-1].value == 1024


def test_addmod_mulmod():
    state = make_state()
    state.mstate.stack.append(bv(7))
    state.mstate.stack.append(bv(6))
    state.mstate.stack.append(bv(5))
    assert run_op(state, "ADDMOD").mstate.stack.pop().value == (5 + 6) % 7

    state.mstate.stack.append(bv(7))
    state.mstate.stack.append(bv(6))
    state.mstate.stack.append(bv(5))
    assert run_op(state, "MULMOD").mstate.stack.pop().value == (5 * 6) % 7


def test_sstore_in_static_call_raises():
    state = make_state(static=True)
    state.mstate.stack.append(bv(1))
    state.mstate.stack.append(bv(0))
    with pytest.raises(WriteProtection):
        Instruction("SSTORE", None).evaluate(state)


def test_sload_after_sstore():
    state = make_state()
    state.mstate.stack.append(bv(42))  # value
    state.mstate.stack.append(bv(3))  # key
    out = run_op(state, "SSTORE")
    out.mstate.stack.append(bv(3))
    out2 = run_op(out, "SLOAD")
    assert out2.mstate.stack[-1].value == 42


def test_mstore_mload_roundtrip():
    state = make_state()
    state.mstate.stack.append(bv(0xDEADBEEF))  # value
    state.mstate.stack.append(bv(64))  # offset
    out = run_op(state, "MSTORE")
    out.mstate.stack.append(bv(64))
    out2 = run_op(out, "MLOAD")
    assert out2.mstate.stack[-1].value == 0xDEADBEEF


def test_mstore8():
    state = make_state()
    state.mstate.stack.append(bv(0x1234))  # only low byte written
    state.mstate.stack.append(bv(10))
    out = run_op(state, "MSTORE8")
    assert out.mstate.memory[10] == 0x34


def test_dup_swap():
    state = make_state()
    state.mstate.stack.append(bv(1))
    state.mstate.stack.append(bv(2))
    out = run_op(state, "DUP2")
    assert out.mstate.stack[-1].value == 1

    out.mstate.stack.pop()
    out2 = run_op(out, "SWAP1")
    assert out2.mstate.stack[-1].value == 1
    assert out2.mstate.stack[-2].value == 2


def test_stack_ops_increment_pc():
    state = make_state()
    state.mstate.stack.append(bv(5))
    pc_before = state.mstate.pc
    out = run_op(state, "POP")
    assert out.mstate.pc == pc_before + 1


def test_sha3_concrete():
    from mythril_tpu.support.keccak import keccak256

    state = make_state()
    # store a known word, hash 32 bytes at offset 0
    state.mstate.stack.append(bv(1))
    state.mstate.stack.append(bv(0))
    out = run_op(state, "MSTORE")
    out.mstate.stack.append(bv(32))  # length
    out.mstate.stack.append(bv(0))  # offset
    out2 = run_op(out, "SHA3")
    expected = int.from_bytes(keccak256((1).to_bytes(32, "big")), "big")
    assert out2.mstate.stack[-1].value == expected


def test_jumpi_forks_two_states():
    # 6000 35 600a 57 00 ... 5b 00  (CALLDATALOAD cond -> JUMPI)
    from mythril_tpu.laser.ethereum.state.calldata import SymbolicCalldata

    code = "6000356008575b00"
    state = make_state(code)
    state.environment.calldata = SymbolicCalldata("1")
    cond = state.environment.calldata.get_word_at(0)
    state.mstate.stack.append(cond)  # condition (symbolic)
    state.mstate.stack.append(bv(5))  # dest -> address 5? adjust below
    # find the JUMPDEST address from the disassembly
    dest = None
    for ins in state.environment.code.instruction_list:
        if ins["opcode"] == "JUMPDEST":
            dest = ins["address"]
    state.mstate.stack.pop()
    state.mstate.stack.append(bv(dest))
    states = Instruction("JUMPI", None).evaluate(state)
    assert len(states) == 2


def test_mulmod_wide_residues():
    """MULMOD computes at 512 bits: residue products that overflow 256
    bits must still be exact (the upstream truncating formula diverges
    here — found by engine-differential testing)."""
    a = 2**255 + 12345
    b_val = 2**254 + 999
    m = 2**256 - 189
    state = make_state()
    state.mstate.stack.append(bv(m))
    state.mstate.stack.append(bv(b_val))
    state.mstate.stack.append(bv(a))
    out = run_op(state, "MULMOD")
    assert out.mstate.stack[-1].value == (a * b_val) % m


def test_addmod_wide_residues():
    a = 2**256 - 5
    b_val = 2**256 - 7
    m = 2**256 - 3
    state = make_state()
    state.mstate.stack.append(bv(m))
    state.mstate.stack.append(bv(b_val))
    state.mstate.stack.append(bv(a))
    out = run_op(state, "ADDMOD")
    assert out.mstate.stack[-1].value == (a + b_val) % m


def test_signextend_accepts_bool_operand():
    """A comparison result (Bool) on the stack must coerce, not crash
    (found by engine-differential testing)."""
    state = make_state()
    state.mstate.stack.append(bv(3))
    state.mstate.stack.append(bv(5))
    mid = run_op(state, "LT")  # pushes a Bool
    mid.mstate.stack.append(bv(0))
    # stack: [..., Bool, 0] -> SIGNEXTEND(0, Bool)
    mid.mstate.stack[-1], mid.mstate.stack[-2] = (
        mid.mstate.stack[-2],
        mid.mstate.stack[-1],
    )
    out = run_op(mid, "SIGNEXTEND")
    assert out.mstate.stack[-1].value in (0, 2**256 - 1)
