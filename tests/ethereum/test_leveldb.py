"""RLP codec + Merkle-Patricia trie reader tests over an in-memory
store (the reference integration-tests against a real geth LevelDB;
an injected dict store exercises the same read paths hermetically)."""

import pytest

from mythril_tpu.ethereum.interface.leveldb import rlp_codec as rlp
from mythril_tpu.ethereum.interface.leveldb.trie import Trie
from mythril_tpu.support.keccak import keccak256


class DictDB:
    def __init__(self):
        self.store = {}

    def get(self, key):
        return self.store.get(key)

    def put(self, key, value):
        self.store[key] = value


# -- RLP ------------------------------------------------------------------
def test_rlp_roundtrip_scalars():
    for item in [b"", b"\x01", b"dog", b"\x80", bytes(100)]:
        assert rlp.decode(rlp.encode(item)) == item


def test_rlp_roundtrip_nested():
    item = [b"cat", [b"dog", b""], [[b"\x01"], b"\xff" * 60]]
    assert rlp.decode(rlp.encode(item)) == item


def test_rlp_known_vectors():
    # canonical vectors from the Ethereum wiki
    assert rlp.encode(b"dog") == b"\x83dog"
    assert rlp.encode([b"cat", b"dog"]) == b"\xc8\x83cat\x83dog"
    assert rlp.encode(b"") == b"\x80"
    assert rlp.encode([]) == b"\xc0"
    assert rlp.encode(15) == b"\x0f"
    assert rlp.encode(1024) == b"\x82\x04\x00"


# -- trie -----------------------------------------------------------------
def build_trie(items):
    """Construct a hexary trie bottom-up in a dict store and return
    (db, root). Uses the simple always-hash node encoding — the reader
    accepts both hashed and embedded nodes."""
    from collections import defaultdict

    db = DictDB()

    def to_nibbles(key):
        out = []
        for b in key:
            out += [b >> 4, b & 0x0F]
        return out

    def hp_encode(nibbles, is_leaf):
        flag = 2 if is_leaf else 0
        if len(nibbles) % 2:
            flag += 1
            data = [flag] + nibbles
        else:
            data = [flag, 0] + nibbles
        return bytes(
            (data[i] << 4) | data[i + 1] for i in range(0, len(data), 2)
        )

    def store(node):
        raw = rlp.encode(node)
        h = keccak256(raw)
        db.put(h, raw)
        return h

    def insert(items):
        # items: list of (nibble-list, value)
        if not items:
            return b""
        if len(items) == 1:
            nibbles, value = items[0]
            return store([hp_encode(nibbles, True), value])
        # group by first nibble
        groups = defaultdict(list)
        value_here = b""
        for nibbles, value in items:
            if not nibbles:
                value_here = value
            else:
                groups[nibbles[0]].append((nibbles[1:], value))
        branch = [b""] * 17
        for nib, sub in groups.items():
            branch[nib] = insert(sub)
        branch[16] = value_here
        return store(branch)

    root = insert([(to_nibbles(k), v) for k, v in items])
    return db, root


def test_trie_get_and_iterate():
    items = [
        (keccak256(b"alpha"), b"value-a"),
        (keccak256(b"beta"), b"value-b"),
        (keccak256(b"gamma"), b"value-c"),
    ]
    db, root = build_trie(items)
    trie = Trie(db, root)

    for key, value in items:
        assert trie.get(key) == value
    assert trie.get(keccak256(b"missing")) is None

    found = dict(trie.iter_items())
    assert found == dict(items)


def test_trie_empty_root():
    trie = Trie(DictDB(), b"")
    assert trie.get(b"\x00" * 32) is None
    assert list(trie.iter_items()) == []


# -- state over trie ------------------------------------------------------
def test_state_account_read():
    from mythril_tpu.ethereum.interface.leveldb.state import State

    address = bytes.fromhex("deadbeef" * 5)
    code = bytes.fromhex("33ff")
    code_hash = keccak256(code)
    account_rlp = rlp.encode(
        [1, 10**18, keccak256(rlp.encode(b"")), code_hash]
    )
    db, root = build_trie([(keccak256(address), account_rlp)])
    db.put(code_hash, code)

    state = State(db, root)
    account = state.get_and_cache_account(address)
    assert account.nonce == 1
    assert account.balance == 10**18
    assert account.code == code

    accounts = list(state.get_all_accounts())
    assert len(accounts) == 1
    assert accounts[0].code == code
