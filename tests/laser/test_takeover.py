"""Host takeover: device lanes resumed mid-frame by the object engine.

The device engine marks CALL-family / over-capacity work UNSUPPORTED
and stops AT the instruction; takeover.py lifts the lane (pc, stack,
memory, storage journal, gas bounds) into a host GlobalState and the
LASER engine finishes the transaction with full reference semantics.
"""

import numpy as np
import pytest

from mythril_tpu.laser.batch.run import run
from mythril_tpu.laser.batch.state import Status, make_batch, make_code_table
from mythril_tpu.laser.batch.takeover import resume_on_host

# store sha256("") via the precompile at address 2, then return it:
#   CALL(gas=50000, to=2, value=0, in=0/0, out=0/32); SSTORE(0, M[0])
SHA256_CALL = bytes(
    [0x60, 0x20,            # PUSH1 32    (out size)
     0x60, 0x00,            # PUSH1 0     (out offset)
     0x60, 0x00,            # PUSH1 0     (in size)
     0x60, 0x00,            # PUSH1 0     (in offset)
     0x60, 0x00,            # PUSH1 0     (value)
     0x60, 0x02,            # PUSH1 2     (sha256 precompile)
     0x61, 0xC3, 0x50,      # PUSH2 50000 (gas)
     0xF1,                  # CALL
     0x50,                  # POP retval
     0x60, 0x00, 0x51,      # MLOAD(0)
     0x60, 0x00, 0x55,      # SSTORE(0, digest)
     0x00]                  # STOP
)

SHA256_EMPTY = int(
    "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855", 16
)


def test_call_lane_resumes_on_host():
    table = make_code_table([SHA256_CALL])
    batch = make_batch(1, gas_budget=1_000_000)
    out, _ = run(batch, table, max_steps=64)
    assert int(out.status[0]) == Status.UNSUPPORTED  # stopped AT the CALL
    # the CALL's seven operands are still on the stack, untouched
    assert int(out.sp[0]) == 7

    outcome = resume_on_host(SHA256_CALL.hex(), out, 0)
    assert outcome is not None and outcome["open"]
    assert outcome["storage"] == {0: SHA256_EMPTY}


def test_journal_and_memory_survive_the_lift():
    # SSTORE(5, 0xAB); MSTORE(0, 0xCD); then hit a CALL -> takeover;
    # host finishes with SSTORE(6, M[0])
    code = bytes(
        [0x60, 0xAB, 0x60, 0x05, 0x55,        # SSTORE(5, 0xAB)
         0x60, 0xCD, 0x60, 0x00, 0x52,        # MSTORE(0, 0xCD)
         0x60, 0x00, 0x60, 0x00, 0x60, 0x00,  # out sz/off, in sz
         0x60, 0x00, 0x60, 0x00, 0x60, 0x02,  # in off, value, to=2
         0x61, 0xC3, 0x50, 0xF1, 0x50,        # gas, CALL, POP
         0x60, 0x00, 0x51, 0x60, 0x06, 0x55,  # SSTORE(6, MLOAD(0))
         0x00]
    )
    table = make_code_table([code])
    batch = make_batch(1, gas_budget=1_000_000)
    out, _ = run(batch, table, max_steps=64)
    assert int(out.status[0]) == Status.UNSUPPORTED

    outcome = resume_on_host(code.hex(), out, 0)
    assert outcome is not None and outcome["open"]
    assert outcome["storage"] == {5: 0xAB, 6: 0xCD}
