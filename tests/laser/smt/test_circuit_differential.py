"""Per-op circuit differential: the bit-blasted circuit of every
operator must agree with the host evaluator on dense input samples.

This is the test family that caught the majority-gate constant bug
(g_maj returning a constant when a TRUE and a FALSE input cancel):
inputs are forced via unit clauses, so the SAT solve is pure
propagation and each op gets edge values plus random samples.
"""

import random

import pytest

from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.evalterm import eval_term
from mythril_tpu.laser.smt.solver import native_sat
from mythril_tpu.laser.smt.solver.bitblast import Blaster

W = 6
EDGES = [0, 1, 2, 3, (1 << W) - 1, (1 << W) - 2, 1 << (W - 1), (1 << (W - 1)) - 1]
RNG = random.Random(2024)
SAMPLES = [(x, y) for x in EDGES for y in EDGES] + [
    (RNG.getrandbits(W), RNG.getrandbits(W)) for _ in range(40)
]

BV_OPS = {
    "add": terms.add,
    "sub": terms.sub,
    "mul": terms.mul,
    "udiv": terms.udiv,
    "urem": terms.urem,
    "and": terms.bvand,
    "or": terms.bvor,
    "xor": terms.bvxor,
    "shl": terms.shl,
    "lshr": terms.lshr,
    "ashr": terms.ashr,
    "ite(ult)": lambda a, b: terms.ite(terms.ult(a, b), terms.add(a, b), terms.sub(a, b)),
    "concat-extract": lambda a, b: terms.extract(
        2 * W - 2, 1, terms.concat(a, b)
    ),
    "sext": lambda a, b: terms.add(
        terms.sext(terms.extract(2, 0, a), W - 3), b
    ),
}
BOOL_OPS = {
    "eq": terms.eq,
    "ult": terms.ult,
    "ule": terms.ule,
    "slt": terms.slt,
    "sle": terms.sle,
}


def _force_and_read(expr, x_t, y_t, xv, yv):
    blaster = Blaster()
    out_bits = (
        [blaster.blast_bool(expr)]
        if expr.sort.kind == "bool"
        else blaster.blast_bv(expr)
    )
    units = []
    for var_t, value in ((x_t, xv), (y_t, yv)):
        for i, lit in enumerate(blaster.blast_bv(var_t)):
            if lit in (1, -1):
                continue
            units.append(lit if (value >> i) & 1 else -lit)
    status, model = native_sat.solve_flat(
        blaster.nvars, blaster.flat, units, 4000
    )
    assert status == native_sat.SAT
    value = 0
    for i, lit in enumerate(out_bits):
        bit = (
            1
            if lit == 1
            else 0
            if lit == -1
            else model[abs(lit) - 1] ^ (1 if lit < 0 else 0)
        )
        if bit:
            value |= 1 << i
    return value


@pytest.mark.parametrize("name", sorted(BV_OPS))
def test_bv_circuit_matches_host(name):
    build = BV_OPS[name]
    x_t = terms.bv_var(f"cd_{name}_x", W)
    y_t = terms.bv_var(f"cd_{name}_y", W)
    expr = build(x_t, y_t)
    for xv, yv in SAMPLES:
        got = _force_and_read(expr, x_t, y_t, xv, yv)
        want = eval_term(expr, {x_t.args[0]: xv, y_t.args[0]: yv})
        assert got == want, f"{name}({xv},{yv}): circuit {got} != host {want}"


@pytest.mark.parametrize("name", sorted(BOOL_OPS))
def test_bool_circuit_matches_host(name):
    build = BOOL_OPS[name]
    x_t = terms.bv_var(f"cb_{name}_x", W)
    y_t = terms.bv_var(f"cb_{name}_y", W)
    expr = build(x_t, y_t)
    for xv, yv in SAMPLES:
        got = _force_and_read(expr, x_t, y_t, xv, yv)
        want = int(bool(eval_term(expr, {x_t.args[0]: xv, y_t.args[0]: yv})))
        assert got == want, f"{name}({xv},{yv}): circuit {got} != host {want}"
