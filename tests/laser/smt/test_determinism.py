"""Determinism regressions: report bytes must be a pure function of
the input.

Two properties are pinned here:

1. **Annotation iteration order is insertion order.** Taint
   annotations hash by object identity; iterating a plain `set` of
   them follows allocator addresses, which vary run to run. The
   integer module's issue dedupe picks whichever taint it sees first,
   so allocator order leaked into report bytes (observed: a witness
   calldata length oscillating 37/48 across identical runs).
   `OrderedSet` (laser/smt/expression.py) replaces the plain set.

2. **Conflict-budgeted solving.** The sprint always, and under
   `--deterministic-solving` the marathon and objective refinement
   too, are budgeted in CDCL conflicts — the same query stream gives
   the same verdicts on any machine at any load.
"""

from __future__ import annotations

from mythril_tpu.laser.smt import symbol_factory
from mythril_tpu.laser.smt.expression import OrderedSet


class _Tag:
    """Identity-hashed annotation stand-in."""


def test_ordered_set_is_insertion_ordered():
    tags = [_Tag() for _ in range(64)]
    s = OrderedSet()
    for t in tags:
        s.add(t)
        s.add(t)  # re-add must not move it
    assert list(s) == tags
    assert len(s) == 64


def test_ordered_set_union_preserves_order():
    a, b, c, d = _Tag(), _Tag(), _Tag(), _Tag()
    left = OrderedSet([a, b])
    right = OrderedSet([c, b, d])
    merged = left | right
    assert list(merged) == [a, b, c, d]
    left |= right
    assert list(left) == [a, b, c, d]
    assert OrderedSet([a]).union([b], [c]) == {a, b, c}
    assert list(OrderedSet([a]).union([b], [c])) == [a, b, c]


def test_ordered_set_equals_plain_set():
    a, b = _Tag(), _Tag()
    assert OrderedSet([a, b]) == {b, a}
    assert OrderedSet([a]) != {a, b}


def test_annotations_propagate_in_insertion_order():
    """Binary ops union annotations left-to-right, deterministically."""
    x = symbol_factory.BitVecSym("detx", 256)
    y = symbol_factory.BitVecSym("dety", 256)
    tx, ty = _Tag(), _Tag()
    x.annotate(tx)
    y.annotate(ty)
    assert list((x + y).annotations) == [tx, ty]
    assert list((y + x).annotations) == [ty, tx]
    from mythril_tpu.laser.smt import Concat, Extract

    assert list(Concat(x, y).annotations) == [tx, ty]
    assert list(Extract(7, 0, x + y).annotations) == [tx, ty]


def test_integer_module_taint_collection_is_ordered():
    from mythril_tpu.analysis.module.modules.integer import (
        OverUnderflowStateAnnotation,
    )

    flow = OverUnderflowStateAnnotation()
    tags = [_Tag() for _ in range(16)]
    for t in tags:
        flow.overflowing_state_annotations[t] = None
    assert list(flow.overflowing_state_annotations) == tags
    from copy import copy

    twin = copy(flow)
    assert list(twin.overflowing_state_annotations) == tags
    twin.overflowing_state_annotations[_Tag()] = None
    assert len(flow.overflowing_state_annotations) == 16  # copy detached


def test_sprint_and_deterministic_marathon_budgets(monkeypatch):
    """Behavioral pin on the conflict-budget discipline: the sprint
    always passes a conflict budget to the native session, and under
    --deterministic-solving the MARATHON does too (timeout_ms * 8),
    with the full caller budget as its wall valve rather than the
    sprint-depleted remainder. The sprint's verdict is forced to
    UNKNOWN so the query genuinely falls through to the marathon
    branch."""
    from mythril_tpu.laser.smt import terms
    from mythril_tpu.laser.smt.solver import native_sat
    from mythril_tpu.laser.smt.solver import solver as S
    from mythril_tpu.support.support_args import args

    calls = []
    real_solve = native_sat.SolverSession.solve

    def recording(self, nvars, flat, units, timeout_ms=None, conflict_budget=None):
        calls.append((timeout_ms, conflict_budget))
        if conflict_budget == S.SPRINT_CONFLICTS:
            # force the sprint to "not finished" so the query genuinely
            # falls through to the marathon branch under test
            return native_sat.UNKNOWN, None
        return real_solve(
            self, nvars, flat, units,
            timeout_ms=timeout_ms, conflict_budget=conflict_budget,
        )

    monkeypatch.setattr(native_sat.SolverSession, "solve", recording)
    monkeypatch.setattr(args, "deterministic_solving", True)
    S.reset_blast_session()

    x = terms.bv_var("detmode_x", 64)
    query = [
        terms.ult(terms.bv_const(10, 64), x),
        terms.ult(x, terms.bv_const(100, 64)),
    ]
    status, model = S.check_terms(query, timeout_ms=10_000)
    assert status == "sat"
    xv = model.assignment.get("detmode_x")
    assert xv is not None and 10 < xv < 100

    # call 1: the sprint, conflict-budgeted with the module constant;
    # call 2: the deterministic marathon with budget timeout_ms*8 and
    # the FULL caller wall valve (not the sprint-depleted remainder)
    assert len(calls) == 2, calls
    assert calls[0][1] == S.SPRINT_CONFLICTS
    assert calls[1][1] == 10_000 * 8
    assert calls[1][0] == 10_000

    # and the verdict repeats bit-identically
    status2, model2 = S.check_terms(query, timeout_ms=10_000)
    assert status2 == "sat"
    assert model2.assignment.get("detmode_x") == xv
