"""Term DAG: constant folding, simplification, evaluation.

Mirrors the role of the reference's tests/laser/smt tests, plus
property tests of the evaluator against Python integer semantics.
"""

import random

import pytest

from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.evalterm import eval_term

W = 256
MASK = (1 << W) - 1


def const(v):
    return terms.bv_const(v, W)


def test_constant_folding_basics():
    a, b = const(7), const(5)
    assert terms.add(a, b).value == 12
    assert terms.sub(b, a).value == (5 - 7) & MASK
    assert terms.mul(a, b).value == 35
    assert terms.udiv(a, b).value == 1
    assert terms.udiv(a, const(0)).value == 0  # EVM x/0 = 0
    assert terms.urem(a, const(0)).value == 0
    assert terms.eq(a, a) is terms.TRUE
    assert terms.ult(b, a) is terms.TRUE
    assert terms.ult(a, a) is terms.FALSE


def test_hash_consing():
    x = terms.bv_var("x", W)
    assert terms.add(x, const(1)) is terms.add(x, const(1))
    assert terms.add(x, const(0)) is x
    assert terms.mul(x, const(1)) is x
    assert terms.mul(x, const(0)).value == 0
    assert terms.bvand(x, const(0)).value == 0
    assert terms.bvand(x, const(MASK)) is x
    assert terms.sub(x, x).value == 0
    assert terms.bvxor(x, x).value == 0


def test_bool_simplification():
    p = terms.bool_var("p")
    assert terms.band(p, terms.TRUE) is p
    assert terms.band(p, terms.FALSE) is terms.FALSE
    assert terms.bor(p, terms.TRUE) is terms.TRUE
    assert terms.bnot(terms.bnot(p)) is p
    assert terms.band(p, terms.bnot(p)) is terms.FALSE
    assert terms.bor(p, terms.bnot(p)) is terms.TRUE


def test_extract_concat_rules():
    x = terms.bv_var("x", W)
    lo = terms.extract(127, 0, x)
    hi = terms.extract(255, 128, x)
    assert terms.concat(hi, lo) is x
    e = terms.extract(15, 8, terms.extract(31, 0, x))
    assert e is terms.extract(15, 8, x)


def test_select_store():
    arr = terms.array_var("storage", 256, 256)
    k1, k2 = const(1), const(2)
    v = const(0xBEEF)
    a2 = terms.store(arr, k1, v)
    assert terms.select(a2, k1) is v
    assert terms.select(a2, k2).op == "select"
    karr = terms.const_array(const(0), 256)
    assert terms.select(karr, terms.bv_var("i", W)).value == 0


_OPS = [
    ("add", terms.add, lambda a, b: (a + b) & MASK),
    ("sub", terms.sub, lambda a, b: (a - b) & MASK),
    ("mul", terms.mul, lambda a, b: (a * b) & MASK),
    ("udiv", terms.udiv, lambda a, b: (a // b) if b else 0),
    ("urem", terms.urem, lambda a, b: (a % b) if b else 0),
    ("and", terms.bvand, lambda a, b: a & b),
    ("or", terms.bvor, lambda a, b: a | b),
    ("xor", terms.bvxor, lambda a, b: a ^ b),
]


@pytest.mark.parametrize("name,op,pyop", _OPS, ids=[o[0] for o in _OPS])
def test_eval_matches_python(name, op, pyop):
    rng = random.Random(name)
    x = terms.bv_var("x", W)
    y = terms.bv_var("y", W)
    t = op(x, y)
    for _ in range(50):
        a = rng.getrandbits(W)
        b = rng.getrandbits(W) if rng.random() < 0.7 else rng.getrandbits(8)
        assert eval_term(t, {"x": a, "y": b}) == pyop(a, b)


def test_eval_signed_ops():
    rng = random.Random(42)
    x = terms.bv_var("x", W)
    y = terms.bv_var("y", W)

    def sgn(v):
        return v - (1 << W) if v >> (W - 1) else v

    for _ in range(100):
        a, b = rng.getrandbits(W), rng.getrandbits(W)
        asn = {"x": a, "y": b}
        sa, sb = sgn(a), sgn(b)
        if sb != 0:
            q = abs(sa) // abs(sb)
            if (sa < 0) != (sb < 0):
                q = -q
            assert eval_term(terms.sdiv(x, y), asn) == q & MASK
            r = abs(sa) % abs(sb)
            if sa < 0:
                r = -r
            assert eval_term(terms.srem(x, y), asn) == r & MASK
        assert eval_term(terms.slt(x, y), asn) == int(sa < sb)
        sh = b % 300
        asn2 = {"x": a, "y": sh}
        assert eval_term(terms.shl(x, y), asn2) == ((a << sh) & MASK if sh < W else 0)
        assert eval_term(terms.lshr(x, y), asn2) == (a >> sh if sh < W else 0)
        assert eval_term(terms.ashr(x, y), asn2) == (sgn(a) >> min(sh, W)) & MASK
