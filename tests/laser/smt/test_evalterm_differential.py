"""evalterm vs raw Python semantics, exhaustively at 6 bits.

eval_term is the ground truth for the solver soundness gate, the
circuit differentials and the portfolio checks — this test anchors it
to first-principles Python integer semantics so the whole chain
(device interpreter == CNF circuits == eval_term == Python) is closed.
"""

import pytest

from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.evalterm import eval_term

W = 6
M = 1 << W


def sgn(v):
    return v - M if v >= M // 2 else v


PY_OPS = {
    "add": (terms.add, lambda a, b: (a + b) % M),
    "sub": (terms.sub, lambda a, b: (a - b) % M),
    "mul": (terms.mul, lambda a, b: (a * b) % M),
    "udiv": (terms.udiv, lambda a, b: 0 if b == 0 else a // b),
    "urem": (terms.urem, lambda a, b: 0 if b == 0 else a % b),
    "sdiv": (
        terms.sdiv,
        lambda a, b: 0
        if b == 0
        else (abs(sgn(a)) // abs(sgn(b)) * (1 if sgn(a) * sgn(b) >= 0 else -1)) % M,
    ),
    "srem": (
        terms.srem,
        lambda a, b: 0
        if b == 0
        else (abs(sgn(a)) % abs(sgn(b)) * (1 if sgn(a) >= 0 else -1)) % M,
    ),
    "and": (terms.bvand, lambda a, b: a & b),
    "or": (terms.bvor, lambda a, b: a | b),
    "xor": (terms.bvxor, lambda a, b: a ^ b),
    "shl": (terms.shl, lambda a, b: (a << b) % M if b < W else 0),
    "lshr": (terms.lshr, lambda a, b: a >> b if b < W else 0),
    "ashr": (
        terms.ashr,
        lambda a, b: (sgn(a) >> b) % M if b < W else (0 if sgn(a) >= 0 else M - 1),
    ),
}
PY_BOOL = {
    "eq": (terms.eq, lambda a, b: a == b),
    "ult": (terms.ult, lambda a, b: a < b),
    "ule": (terms.ule, lambda a, b: a <= b),
    "slt": (terms.slt, lambda a, b: sgn(a) < sgn(b)),
    "sle": (terms.sle, lambda a, b: sgn(a) <= sgn(b)),
}


@pytest.mark.parametrize("name", sorted(PY_OPS))
def test_evalterm_bv_op(name):
    build, py = PY_OPS[name]
    x = terms.bv_var(f"ev_{name}_x", W)
    y = terms.bv_var(f"ev_{name}_y", W)
    expr = build(x, y)
    for a in range(M):
        for b in range(M):
            got = eval_term(expr, {x.args[0]: a, y.args[0]: b})
            want = py(a, b)
            assert got == want, f"{name}({a},{b}): {got} != {want}"


@pytest.mark.parametrize("name", sorted(PY_BOOL))
def test_evalterm_bool_op(name):
    build, py = PY_BOOL[name]
    x = terms.bv_var(f"eb_{name}_x", W)
    y = terms.bv_var(f"eb_{name}_y", W)
    expr = build(x, y)
    for a in range(M):
        for b in range(M):
            got = bool(eval_term(expr, {x.args[0]: a, y.args[0]: b}))
            assert got == py(a, b), f"{name}({a},{b})"


def test_evalterm_extract_concat_sext():
    x = terms.bv_var("ev_misc_x", W)
    for a in range(M):
        asn = {"ev_misc_x": a}
        assert eval_term(terms.extract(4, 2, x), asn) == (a >> 2) & 0b111
        assert eval_term(terms.concat(x, terms.bv_const(0b11, 2)), asn) == (
            (a << 2) | 0b11
        )
        low3 = a & 0b111
        expected = (low3 | (~0b111 % M if low3 & 0b100 else 0)) % M
        assert eval_term(terms.sext(terms.extract(2, 0, x), W - 3), asn) == expected
