"""Race-cone and witness-extension units: the solver-race support
machinery that must stay correct regardless of whether a chip is
present (the race itself is raced only on accelerator backends)."""

from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.solver.solver import _race_cone, check_terms, sat


def test_small_sets_pass_through():
    x = terms.bv_var("rc_x", 64)
    cs = [terms.ult(x, terms.bv_const(5, 64))]
    assert _race_cone(cs) == cs


def test_cone_keeps_tail_and_connected_constraints():
    W = 64
    x = terms.bv_var("rc2_x", W)
    y = terms.bv_var("rc2_y", W)
    # 600 unrelated conjuncts over other vars + 2 tail conjuncts on x,y
    noise = [
        terms.ult(terms.bv_var(f"rc2_n{i}", W), terms.bv_const(i + 1, W))
        for i in range(600)
    ]
    bridge = terms.ult(x, terms.bv_var("rc2_n0", W))  # links x to n0
    tail = [terms.eq(terms.mul(x, y), terms.bv_const(42, W)),
            terms.bnot(terms.eq(y, terms.bv_const(0, W)))]
    cone = _race_cone(noise + [bridge] + tail, max_constraints=64)
    assert tail[0] in cone and tail[1] in cone
    assert bridge in cone  # shares x with the tail
    assert len(cone) <= 64


def test_cone_subset_preserves_order():
    W = 32
    vs = [terms.bv_var(f"rc3_{i}", W) for i in range(6)]
    chain = [terms.ult(vs[i], vs[i + 1]) for i in range(5)]
    pad = [
        terms.ult(terms.bv_var(f"rc3_p{i}", W), terms.bv_const(1, W))
        for i in range(500)
    ]
    cone = _race_cone(pad + chain, max_constraints=32)
    idx = [cone.index(c) for c in chain if c in cone]
    assert idx == sorted(idx)


def test_check_terms_still_sound_on_hard_shape():
    """The BEC-guard shape must stay solvable through the public
    surface with the race machinery compiled in (host CDCL answers on
    CPU backends; on accelerator backends a race may win instead —
    either way the verdict is sat with a validated model)."""
    W = 64  # narrow width keeps the CPU solve fast
    x = terms.bv_var("rc4_x", W)
    y = terms.bv_var("rc4_y", W)
    q = terms.udiv(terms.mul(x, y), y)
    verdict, model = check_terms(
        [terms.bnot(terms.eq(q, x)),
         terms.bnot(terms.eq(y, terms.bv_const(0, W)))],
        timeout_ms=30_000,
    )
    assert verdict == sat
    xa = model.assignment["rc4_x"]
    ya = model.assignment["rc4_y"]
    assert ya != 0
    assert ((xa * ya) % (1 << W)) // ya != xa
