"""On-chip portfolio solver tests (CPU backend; the compiled program
and local search run identically on TPU)."""

import pytest

from mythril_tpu.laser.smt import ULT, symbol_factory
from mythril_tpu.laser.smt.evalterm import eval_term
from mythril_tpu.laser.smt.solver.portfolio import (
    compile_program,
    debug_eval,
    device_check,
)
from mythril_tpu.laser.smt.solver.solver import lower


def bv(name, width=256):
    return symbol_factory.BitVecSym(name, width)


def lowered(*constraints):
    out, _ = lower([c.raw for c in constraints])
    return out


def test_interpreter_matches_host_eval():
    x, y = bv("px", 64), bv("py", 64)
    cons = lowered(x + y == 100, ULT(x, y), x * 2 == y - 10)
    prog = compile_program(cons)
    assert prog is not None
    # x=30, y=70: 30+70=100, 30<70, 60 == 60
    solved, _ = debug_eval(prog, {"px": 30, "py": 70})
    assert solved
    solved_bad, _ = debug_eval(prog, {"px": 31, "py": 69})
    assert not solved_bad


def test_soft_score_gradient():
    x = bv("gx", 64)
    prog = compile_program(lowered(x + 5 == 12))
    _, perfect = debug_eval(prog, {"gx": 7})
    _, close = debug_eval(prog, {"gx": 6})  # 11 vs 12: 3 bits differ
    # 0xAAAA..AA + 5 differs from 12 in ~half of all 64 bits
    _, far = debug_eval(prog, {"gx": 0xAAAA_AAAA_AAAA_AAAA})
    assert perfect > close > far


def test_search_finds_linear_witness():
    x = bv("sx", 64)
    cons = lowered(x + 5 == 12)
    asn = device_check(cons, candidates=64, steps=4096)
    assert asn is not None
    assert all(eval_term(c, asn) for c in cons)


def test_search_finds_multi_constraint_witness():
    y = bv("sy", 32)
    cons = lowered(y * 3 == 21, ULT(y, 100))
    asn = device_check(cons, candidates=64, steps=4096)
    assert asn is not None
    assert all(eval_term(c, asn) for c in cons)


def test_witness_never_lies():
    """device_check output must always satisfy the constraints (run a
    few shapes; None is acceptable, a wrong witness is not)."""
    a, b = bv("wa", 64), bv("wb", 64)
    for cons in [
        lowered(a - b == 3, ULT(b, 1000)),
        lowered((a & 0xFF) == 0x42),
        lowered(a == b, ULT(a, 10)),
    ]:
        asn = device_check(cons, candidates=32, steps=1024)
        if asn is not None:
            assert all(eval_term(c, asn) for c in cons)


def test_unsupported_ops_return_none():
    from mythril_tpu.laser.smt import terms

    # a raw select is outside the device language (lower() normally
    # removes arrays; feed one directly)
    arr = terms.array_var("A", 256, 256)
    sel = terms.select(arr, terms.bv_var("i", 256))
    cons = [terms.eq(sel, terms.bv_const(5, 256))]
    assert compile_program(cons) is None

# slow tier: ~30 s of full-budget portfolio grinding per test on a
# 1-core host; the multichip suite keeps a fast batched-solve pin
@pytest.mark.slow
def test_batched_dispatch_alignment():
    """device_check_batch answers each query independently in one
    dispatch: results are position-aligned, every returned witness
    satisfies ITS OWN query, and device-language dropouts come back
    None without disturbing their neighbours."""
    from mythril_tpu.laser.smt import terms
    from mythril_tpu.laser.smt.solver.portfolio import device_check_batch

    x, y, z = bv("bx", 64), bv("by", 32), bv("bz", 16)
    queries = [
        lowered(x + 5 == 12),
        lowered(y * 3 == 21, ULT(y, 100)),
        # outside the device language: raw select survives lowering here
        # because it is injected directly
        [
            terms.eq(
                terms.select(
                    terms.array_var("B", 256, 256), terms.bv_var("i", 256)
                ),
                terms.bv_const(5, 256),
            )
        ],
        lowered((z & 0xFF) == 0x42),
    ]
    out = device_check_batch(queries, candidates=64, steps=4096)
    assert len(out) == len(queries)
    assert out[2] is None
    for q, asn in zip(queries, out):
        if asn is None:
            continue
        assert all(eval_term(c, asn) for c in q)
    # the easy linear queries must actually be solved, not skipped
    assert out[0] is not None and out[1] is not None and out[3] is not None


def test_batched_matches_single():
    """A query solved through the batch decodes to a witness exactly as
    valid as the per-query path's."""
    from mythril_tpu.laser.smt.solver.portfolio import device_check_batch

    a, b = bv("ma", 64), bv("mb", 64)
    cons = lowered(a - b == 3, ULT(b, 1000))
    single = device_check(cons, candidates=64, steps=4096)
    batched = device_check_batch([cons, cons], candidates=64, steps=4096)
    for asn in [single] + list(batched):
        if asn is not None:
            assert all(eval_term(c, asn) for c in cons)


@pytest.mark.slow
def test_batched_dispatch_sharded_over_devices():
    """The query axis shards over a device mesh (pmap of the vmapped
    search): same aligned answers, each device solving its chunk."""
    import jax

    from mythril_tpu.laser.smt.solver.portfolio import device_check_batch

    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    qs = [bv(f"sh{i}", 32) for i in range(4)]
    queries = [lowered(q * 3 == 21 + 3 * i) for i, q in enumerate(qs)]
    out = device_check_batch(
        queries, candidates=32, steps=2048, n_devices=jax.device_count()
    )
    assert len(out) == len(queries)
    solved = 0
    for q, asn in zip(queries, out):
        if asn is not None:
            assert all(eval_term(c, asn) for c in q)
            solved += 1
    assert solved >= 1
