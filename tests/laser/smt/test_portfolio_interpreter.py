"""Device-interpreter differential: the portfolio solver's tensor
program must agree with the host evaluator op by op.

compile_program + debug_eval evaluate a constraint under a forced
assignment on device (CPU backend here; identical lowering on TPU);
solved/score must match host evaluation for every sampled input —
including the compiled signed rewrites (slt/sle via sign-bit xor,
sext via xor-sub, ashr via sign-fill masks).
"""

import random

import pytest

from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.evalterm import eval_term
from mythril_tpu.laser.smt.solver.portfolio import compile_program, debug_eval

W = 32
EDGES = [0, 1, 2, (1 << W) - 1, (1 << W) - 2, 1 << (W - 1), (1 << (W - 1)) - 1, 0xDEADBEEF % (1 << W)]
RNG = random.Random(99)
SAMPLES = [(x, y) for x in EDGES for y in EDGES[:4]] + [
    (RNG.getrandbits(W), RNG.getrandbits(W)) for _ in range(24)
]

OPS = {
    "add": terms.add,
    "sub": terms.sub,
    "mul": terms.mul,
    "udiv": terms.udiv,
    "urem": terms.urem,
    "and": terms.bvand,
    "or": terms.bvor,
    "xor": terms.bvxor,
    "shl": terms.shl,
    "lshr": terms.lshr,
    "ashr": terms.ashr,
    "concat-extract": lambda a, b: terms.extract(
        W, 1, terms.concat(a, b)
    ),
    "sext": lambda a, b: terms.add(
        terms.sext(terms.extract(7, 0, a), W - 8), b
    ),
    "ite(slt)": lambda a, b: terms.ite(
        terms.slt(a, b), terms.add(a, b), terms.bvxor(a, b)
    ),
    "ule-word": lambda a, b: terms.ite(
        terms.ule(a, b), terms.bv_const(1, W), terms.bv_const(2, W)
    ),
    "sle-word": lambda a, b: terms.ite(
        terms.sle(a, b), terms.bv_const(1, W), terms.bv_const(2, W)
    ),
}


@pytest.mark.parametrize("name", sorted(OPS))
def test_device_op_matches_host(name):
    build = OPS[name]
    x_t = terms.bv_var(f"dp_{name}_x", W)
    y_t = terms.bv_var(f"dp_{name}_y", W)
    expr = build(x_t, y_t)

    for xv, yv in SAMPLES:
        asn = {x_t.args[0]: xv, y_t.args[0]: yv}
        want = eval_term(expr, asn)
        # constraint "expr == want" must be satisfied under the forced
        # assignment; "expr == want+1" must not
        prog_eq = compile_program([terms.eq(expr, terms.bv_const(want, W))])
        assert prog_eq is not None, name
        solved, _ = debug_eval(prog_eq, asn)
        assert solved, f"{name}({xv},{yv}): device disagrees with host ({want})"

        wrong = (want + 1) % (1 << W)
        prog_ne = compile_program([terms.eq(expr, terms.bv_const(wrong, W))])
        solved_wrong, _ = debug_eval(prog_ne, asn)
        assert not solved_wrong, f"{name}({xv},{yv}): device accepts wrong value"
