"""Solver pipeline: sat/unsat decisions + model soundness.

The reference leans on z3 for all of this (tests/laser/smt/); here the
whole stack (preprocess -> bitblast -> native CDCL -> model
reconstruction) is under test, including EVM-shaped queries of the
kind detection modules pose.
"""

import random

import pytest

from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.smt import (
    And,
    Array,
    BitVec,
    Concat,
    Extract,
    If,
    K,
    Not,
    Or,
    Solver,
    UGE,
    UGT,
    ULT,
    symbol_factory,
)
from mythril_tpu.laser.smt.solver import Optimize, sat, unsat


def bv(name, w=256):
    return symbol_factory.BitVecSym(name, w)


def val(v, w=256):
    return symbol_factory.BitVecVal(v, w)


def check(*constraints, timeout=15000):
    s = Solver(timeout=timeout)
    s.add(*constraints)
    return s.check(), s


def test_trivial_sat_unsat():
    x = bv("x")
    assert check(x == 5)[0] == sat
    assert check(x == 5, x == 6)[0] == unsat
    assert check(symbol_factory.Bool(False))[0] == unsat
    assert check()[0] == sat


def test_model_values():
    x, y = bv("x"), bv("y")
    status, s = check(x == 5, y == x + 10)
    assert status == sat
    m = s.model()
    assert m.eval(x.raw).value == 5
    assert m.eval(y.raw).value == 15


def test_inequality_chain():
    x = bv("x", 16)
    status, s = check(UGT(x, 100), ULT(x, 103), x != 101)
    assert status == sat
    assert s.model().eval(x.raw).value == 102
    assert check(UGT(x, 100), ULT(x, 101))[0] == unsat


def test_addition_overflow_query():
    # the IntegerArithmetics module shape: can a+b wrap?
    a, b = bv("a", 8), bv("b", 8)
    status, s = check(ULT(a + b, a), UGT(b, 0))
    assert status == sat
    m = s.model()
    av, bvv = m.eval(a.raw).value, m.eval(b.raw).value
    assert (av + bvv) % 256 < av


def test_mul_relation():
    a, b = bv("a", 16), bv("b", 16)
    status, s = check(a * b == 77, UGT(a, 1), UGT(b, 1))
    assert status == sat
    m = s.model()
    assert (m.eval(a.raw).value * m.eval(b.raw).value) % (1 << 16) == 77


def test_division():
    a = bv("a", 16)
    status, s = check(a / val(3, 16) == val(5, 16), a % 3 == 1)
    assert status == sat
    assert s.model().eval(a.raw).value == 16


def test_signed_compare():
    x = bv("x", 8)
    status, s = check(x < 0, x > -5)  # signed via overloads
    assert status == sat
    v = s.model().eval(x.raw).value
    assert v >= 0xFB  # -5..-1 two's complement


def test_extract_selector_pattern():
    # the calldata function-selector pattern: Extract == const
    data = bv("calldata", 256)
    sel = Extract(255, 224, data)
    status, s = check(sel == val(0xDEADBEEF, 32))
    assert status == sat
    assert s.model().eval(sel.raw).value == 0xDEADBEEF


def test_arrays_consistency():
    storage = Array("storage", 256, 256)
    i, j = bv("i"), bv("j")
    vi, vj = storage[i], storage[j]
    # same index must read same value
    assert check(i == j, vi != vj)[0] == unsat
    status, s = check(i != j, vi == 5, vj == 7)
    assert status == sat
    m = s.model()
    assert m.eval(vi.raw).value == 5
    assert m.eval(vj.raw).value == 7


def test_store_select():
    storage = Array("s", 256, 256)
    storage[val(3)] = val(0xAA)
    x = bv("x")
    v = storage[x]
    status, s = check(v == 0xAA)
    assert status == sat
    status2, _ = check(x == 3, v != 0xAA)
    assert status2 == unsat


def test_ite():
    c = bv("c")
    r = If(c == 0, val(11), val(22))
    status, s = check(r == 22)
    assert status == sat
    assert s.model().eval(c.raw).value != 0


def test_optimize_minimize():
    x = bv("x", 32)
    s = Optimize(timeout=20000)
    s.add(UGE(x, 1000), ULT(x, 100000))
    s.minimize(x)
    assert s.check() == sat
    assert s.model().eval(x.raw).value == 1000


def test_optimize_maximize():
    x = bv("x", 16)
    s = Optimize(timeout=20000)
    s.add(ULT(x, 1234))
    s.maximize(x)
    assert s.check() == sat
    assert s.model().eval(x.raw).value == 1233


# slow tier: ~100 s of brute-force differential on a 1-core host —
# the 8-bit sweep belongs to the conformance tier (tox -e slow)
@pytest.mark.slow
def test_random_differential():
    """Random constraint systems: solver verdicts vs brute force (8-bit)."""
    rng = random.Random(1337)
    for trial in range(25):
        xs = [bv(f"v{trial}_{i}", 8) for i in range(3)]
        cons = []
        for _ in range(rng.randint(1, 4)):
            a, b = rng.sample(xs, 2)
            kind = rng.randrange(5)
            k = val(rng.getrandbits(8), 8)
            if kind == 0:
                cons.append(a + b == k)
            elif kind == 1:
                cons.append(ULT(a, k))
            elif kind == 2:
                cons.append((a & b) == k)
            elif kind == 3:
                cons.append(a * val(rng.getrandbits(4), 8) == k)
            else:
                cons.append(Or(a == k, b == k))
        status, s = check(*cons)
        # brute force ground truth
        found = False
        for v0 in range(0, 256, 3):
            for v1 in range(0, 256, 3):
                for v2 in range(0, 256, 5):
                    asn = {f"v{trial}_0": v0, f"v{trial}_1": v1, f"v{trial}_2": v2}
                    from mythril_tpu.laser.smt.evalterm import eval_term

                    if all(eval_term(c.raw, asn) for c in cons):
                        found = True
                        break
                if found:
                    break
            if found:
                break
        if found:
            assert status == sat, f"trial {trial}: brute found model, solver said {status}"
        # solver sat with brute miss is fine (sparse brute grid); model
        # soundness is enforced inside check_terms


def test_get_model_cache_and_unsat():
    from mythril_tpu.support.model import clear_cache, get_model

    clear_cache()
    x = bv("gm_x")
    m = get_model((x == 42,), enforce_execution_time=False)
    assert m.eval(x.raw).value == 42
    with pytest.raises(UnsatError):
        get_model((x == 1, x == 2), enforce_execution_time=False)
    # cached unsat raises again
    with pytest.raises(UnsatError):
        get_model((x == 1, x == 2), enforce_execution_time=False)


def test_independence_solver():
    from mythril_tpu.laser.smt import IndependenceSolver

    x, y, z = bv("ix"), bv("iy"), bv("iz")
    s = IndependenceSolver(timeout=20000)
    s.add(x == 5, y == x + 1)  # bucket 1
    s.add(z == 99)  # bucket 2
    assert s.check() == sat
    m = s.model()
    assert m.eval(y.raw).value == 6
    assert m.eval(z.raw).value == 99
    s2 = IndependenceSolver(timeout=20000)
    s2.add(x == 5, z == 1, z == 2)
    assert s2.check() == unsat


def test_store_chain_shared_across_queries():
    """The context-free select-chain cache must not leak bindings
    between queries: the same chain queried under contradictory and
    then satisfiable contexts gives correct verdicts and models."""
    from mythril_tpu.laser.smt import Array, symbol_factory

    storage = Array("xstorage", 256, 256)
    k = symbol_factory.BitVecSym("xq_k", 256)
    storage[symbol_factory.BitVecVal(1, 256)] = symbol_factory.BitVecVal(11, 256)
    storage[symbol_factory.BitVecVal(2, 256)] = symbol_factory.BitVecVal(22, 256)
    read = storage[k]

    # query 1: k == 1 forces read == 11 -> read == 22 is unsat
    assert check(k == 1, read == 22)[0] == unsat
    # query 2 (same chain, new context): k == 2 gives read == 22
    status, s = check(k == 2, read == 22)
    assert status == sat
    # query 3: unknown key reads the base array -> any value reachable
    status, s = check(k == 5, read == 77)
    assert status == sat
    assert s.model().eval(read.raw).value == 77


def test_random_differential_wide_ops():
    """Exhaustive 2-var 6-bit differential over the wider op set
    (shifts, extract, concat, ite, signed compares): the solver verdict
    must match complete enumeration exactly — both directions."""
    from mythril_tpu.laser.smt import Extract, Concat, If, SGT
    from mythril_tpu.laser.smt.evalterm import eval_term

    rng = random.Random(777)
    W = 6
    for trial in range(20):
        x = bv(f"w{trial}_x", W)
        y = bv(f"w{trial}_y", W)
        k1 = val(rng.getrandbits(W), W)
        k2 = val(rng.getrandbits(W), W)
        kind = trial % 5
        if kind == 0:
            cons = [(x << (y & 3)) == k1, ULT(y, 40)]
        elif kind == 1:
            cons = [Extract(3, 1, x) == Extract(2, 0, k1), (x ^ y) == k2]
        elif kind == 2:
            cons = [Concat(Extract(2, 0, x), Extract(2, 0, y)) == k1]
        elif kind == 3:
            cons = [If(ULT(x, y), x + k1, y - k1) == k2]
        else:
            cons = [SGT(x, y), (x & k1) == (y & k1)]

        status, s = check(*cons)

        brute_sat = False
        for vx in range(1 << W):
            for vy in range(1 << W):
                asn = {f"w{trial}_x": vx, f"w{trial}_y": vy}
                if all(eval_term(c.raw, asn) for c in cons):
                    brute_sat = True
                    break
            if brute_sat:
                break

        assert (status == sat) == brute_sat, (
            f"trial {trial} kind {kind}: solver={status} brute_sat={brute_sat}"
        )
        if status == sat:
            m = s.model()
            asn = {
                f"w{trial}_x": m.eval(x.raw).value,
                f"w{trial}_y": m.eval(y.raw).value,
            }
            assert all(eval_term(c.raw, asn) for c in cons)
