"""Regressions for solver-layer bugs found in review: UF-coupled
independence partitioning, assumption scoping, signed-underflow
semantics, and deep-term blasting."""

from mythril_tpu.laser.smt import BVSubNoUnderflow, symbol_factory
from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.solver import IndependenceSolver, Solver, sat, unsat


def test_independence_solver_couples_through_uf():
    # [x==0, keccak(x)==1] and [y==0, keccak(y)==2] share only the UF;
    # solving them separately would wrongly report sat
    x = terms.bv_var("x", 8)
    y = terms.bv_var("y", 8)
    s = IndependenceSolver()
    s.add(terms.eq(x, terms.bv_const(0, 8)))
    s.add(terms.eq(terms.apply_uf("keccak", 8, (x,)), terms.bv_const(1, 8)))
    s.add(terms.eq(y, terms.bv_const(0, 8)))
    s.add(terms.eq(terms.apply_uf("keccak", 8, (y,)), terms.bv_const(2, 8)))
    assert s.check() == unsat


def test_check_assumptions_are_scoped():
    x = symbol_factory.BitVecSym("scoped_x", 8)
    s = Solver()
    s.add(x > 0)
    assert s.check(x == 1) == sat
    # the x==1 probe must not leak into the persistent constraint set
    assert s.check(x == 2) == sat


def test_signed_sub_no_underflow():
    mk = lambda v: symbol_factory.BitVecVal(v, 4)
    # -8 - 1 underflows 4-bit signed range
    assert BVSubNoUnderflow(mk(0x8), mk(1), signed=True).value is False
    # 7 - (-8) overflows but does not *underflow*
    assert BVSubNoUnderflow(mk(7), mk(0x8), signed=True).value is True
    # plain small case
    assert BVSubNoUnderflow(mk(3), mk(2), signed=True).value is True


def test_deep_term_does_not_crash():
    x = symbol_factory.BitVecSym("deep_x", 32)
    acc = x
    for _ in range(3000):
        acc = acc + 1
    s = Solver(timeout=15000)
    s.add(acc == 5)
    assert s.check() in (sat, "unknown")
