"""Native blaster equivalence: the C++ circuit builders
(native/blast.cpp) must produce a BIT-FOR-BIT identical CNF stream to
the pure-Python PyBlaster — same variable numbering, same clause order,
same simplifications. Identical CNF is the invariant that makes the
native path transparent: the CDCL session sees the same clauses, so
verdicts, models, concretized witnesses, and golden report bytes are
unchanged.

The generators below cover every operator the blast fragment admits,
plus randomized DAGs with shared subterms (the gate-cache paths) and
multi-constraint sessions (the persistent-store append path).
"""

import random

import pytest

from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.solver.bitblast import (
    NativeBlaster,
    PyBlaster,
    native_blast_available,
)

pytestmark = pytest.mark.skipif(
    not native_blast_available(), reason="native blast library not built"
)


def _assert_identical(blast_inputs):
    """blast_inputs: list of ('bool'|'bv', term). Blast the same
    sequence through both implementations and compare everything."""
    py, nat = PyBlaster(), NativeBlaster()
    for kind, t in blast_inputs:
        if kind == "bool":
            lp = py.blast_bool(t)
            ln = nat.blast_bool(t)
        else:
            lp = py.blast_bv(t)
            ln = nat.blast_bv(t)
        assert lp == ln, f"root literal mismatch on {t.op}"
    assert py.nvars == nat.nvars
    flat_py = list(py.flat)
    n = len(nat.flat)
    ptr, cnt = nat.flat.window(0)
    flat_nat = [ptr[i] for i in range(cnt)]
    assert n == len(flat_py)
    assert flat_nat == flat_py
    assert py.var_bits == nat.var_bits
    assert py.bool_vars == nat.bool_vars


W = 8


def _vars(w=W):
    return terms.bv_var("nx", w), terms.bv_var("ny", w), terms.bv_var("nz", w)


BV_BUILDERS = [
    lambda x, y, z: terms.add(x, y),
    lambda x, y, z: terms.sub(x, y),
    lambda x, y, z: terms.mul(x, y),
    lambda x, y, z: terms.udiv(x, y),
    lambda x, y, z: terms.urem(x, y),
    lambda x, y, z: terms.bvand(x, y),
    lambda x, y, z: terms.bvor(x, y),
    lambda x, y, z: terms.bvxor(x, y),
    lambda x, y, z: terms.shl(x, y),
    lambda x, y, z: terms.lshr(x, y),
    lambda x, y, z: terms.ashr(x, y),
    lambda x, y, z: terms.bvnot(x),
    lambda x, y, z: terms.ite(terms.ult(x, y), terms.add(x, z), terms.sub(y, z)),
    lambda x, y, z: terms.concat(terms.extract(W - 1, W // 2, x), terms.extract(W // 2 - 1, 0, y)),
    lambda x, y, z: terms.add(terms.zext(terms.extract(3, 0, x), W - 4), y),
    lambda x, y, z: terms.add(terms.sext(terms.extract(3, 0, x), W - 4), y),
    lambda x, y, z: terms.mul(terms.add(x, y), terms.add(x, y)),  # shared subterm
    lambda x, y, z: terms.udiv(terms.add(x, terms.bv_const(0, W)), y),
    lambda x, y, z: terms.add(x, terms.bv_const(0x2B, W)),
    lambda x, y, z: terms.mul(x, terms.bv_const(10, W)),
    lambda x, y, z: terms.shl(x, terms.bv_const(3, W)),
]

BOOL_BUILDERS = [
    lambda x, y, z: terms.eq(x, y),
    lambda x, y, z: terms.ult(x, y),
    lambda x, y, z: terms.ule(x, y),
    lambda x, y, z: terms.slt(x, y),
    lambda x, y, z: terms.sle(x, y),
    lambda x, y, z: terms.band(terms.ult(x, y), terms.eq(y, z)),
    lambda x, y, z: terms.bor(terms.eq(x, z), terms.bnot(terms.ult(z, y))),
    lambda x, y, z: terms.bxor(terms.ult(x, y), terms.ult(y, x)),
    lambda x, y, z: terms.ite(
        terms.eq(x, y), terms.ult(x, z), terms.ule(z, y)
    ),
    lambda x, y, z: terms.eq(terms.mul(x, y), terms.add(z, z)),
    lambda x, y, z: terms.band(
        terms.eq(terms.urem(x, terms.bv_const(7, W)), terms.bv_const(3, W)),
        terms.ult(terms.udiv(x, terms.bv_const(7, W)), y),
    ),
]


@pytest.mark.parametrize("i", range(len(BV_BUILDERS)))
def test_bv_ops_stream_identical(i):
    x, y, z = _vars()
    _assert_identical([("bv", BV_BUILDERS[i](x, y, z))])


@pytest.mark.parametrize("i", range(len(BOOL_BUILDERS)))
def test_bool_ops_stream_identical(i):
    x, y, z = _vars()
    _assert_identical([("bool", BOOL_BUILDERS[i](x, y, z))])


def test_multi_constraint_session_stream_identical():
    """Blasting several constraints into one persistent store — the
    solver-session usage pattern, exercising cross-constraint cache
    hits on vars and shared gates."""
    x, y, z = _vars()
    seq = [
        ("bool", terms.ult(terms.add(x, y), terms.bv_const(100, W))),
        ("bool", terms.eq(terms.mul(x, y), z)),
        ("bool", terms.bnot(terms.eq(x, terms.bv_const(0, W)))),
        ("bool", terms.ule(terms.udiv(z, x), y)),
        # repeat of the first: everything must come from caches, with
        # zero new clauses on both sides
        ("bool", terms.ult(terms.add(x, y), terms.bv_const(100, W))),
    ]
    _assert_identical(seq)


def _random_term(rng, depth, w, pool):
    if depth == 0 or rng.random() < 0.25:
        r = rng.random()
        if r < 0.5:
            return pool[rng.randrange(len(pool))]
        return terms.bv_const(rng.getrandbits(w), w)
    op = rng.choice(
        ["add", "sub", "mul", "and", "or", "xor", "shl", "lshr", "ashr",
         "udiv", "urem", "not", "ite"]
    )
    a = _random_term(rng, depth - 1, w, pool)
    b = _random_term(rng, depth - 1, w, pool)
    if op == "not":
        return terms.bvnot(a)
    if op == "ite":
        c = terms.ult(a, b)
        return terms.ite(c, a, b)
    fn = {
        "add": terms.add, "sub": terms.sub, "mul": terms.mul,
        "and": terms.bvand, "or": terms.bvor, "xor": terms.bvxor,
        "shl": terms.shl, "lshr": terms.lshr, "ashr": terms.ashr,
        "udiv": terms.udiv, "urem": terms.urem,
    }[op]
    return fn(a, b)


@pytest.mark.parametrize("seed", range(8))
def test_random_dags_stream_identical(seed):
    rng = random.Random(1000 + seed)
    w = rng.choice([4, 8, 16])
    pool = [terms.bv_var(f"r{seed}_{i}", w) for i in range(3)]
    constraints = []
    for _ in range(4):
        lhs = _random_term(rng, 3, w, pool)
        rhs = _random_term(rng, 3, w, pool)
        constraints.append(
            ("bool", rng.choice([terms.eq, terms.ult, terms.ule])(lhs, rhs))
        )
    _assert_identical(constraints)


def test_width_256_evm_shapes_stream_identical():
    """Full EVM width: one 256-bit arithmetic constraint set of the
    shape path constraints actually take."""
    x = terms.bv_var("big_x", 256)
    y = terms.bv_var("big_y", 256)
    c = terms.bv_const((1 << 255) + 12345, 256)
    seq = [
        ("bool", terms.ult(terms.add(x, y), x)),          # overflow shape
        ("bool", terms.eq(terms.mul(x, terms.bv_const(2, 256)), c)),
        ("bool", terms.ule(terms.lshr(x, terms.bv_const(4, 256)), y)),
    ]
    _assert_identical(seq)
