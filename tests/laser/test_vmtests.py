"""Conformance: official Ethereum VMTests replayed as one StateBatch.

Mirrors the reference's ground-truth strategy (reference:
tests/laser/evm_testsuite/evm_test.py) but runs the full corpus as a
single batched XLA program instead of one interpreter run per test.
"""

import pytest

from mythril_tpu.laser.conformance import VMTESTS_ROOT, load_vmtests, run_cases

if not VMTESTS_ROOT.is_dir():  # pragma: no cover
    pytest.skip("VMTests vectors not available", allow_module_level=True)

CASES, LOAD_SKIPS = load_vmtests()


@pytest.fixture(scope="module")
def verdicts():
    return run_cases(CASES)


@pytest.mark.parametrize("name", [c.name for c in CASES])
def test_vmtest(name, verdicts):
    v = verdicts[name]
    if v.startswith("skip"):
        pytest.skip(v)
    assert v == "pass", v


def test_conformance_pinned_to_manifest(verdicts):
    """Exact per-suite pass counts + the skip list are pinned in a
    checked-in manifest — a regression in any single suite turns the
    build red (round-1 verdict: a >=300 floor would green-light a 40%
    regression)."""
    import json
    from collections import defaultdict
    from pathlib import Path

    manifest = json.loads(
        (Path(__file__).parent / "vmtests_manifest.json").read_text()
    )

    per_suite = defaultdict(int)
    skipped = {}
    for case in CASES:
        verdict = verdicts[case.name]
        if verdict == "pass":
            per_suite[case.name.split("/")[0]] += 1
        elif verdict.startswith("skip"):
            skipped[case.name] = verdict

    assert dict(per_suite) == manifest["per_suite_pass"]
    assert skipped == manifest["skipped_cases"]
    assert sorted(
        s if isinstance(s, str) else s[0] for s in LOAD_SKIPS
    ) == manifest["load_skipped"]
