"""Conformance: official Ethereum VMTests replayed as one StateBatch.

Mirrors the reference's ground-truth strategy (reference:
tests/laser/evm_testsuite/evm_test.py) but runs the full corpus as a
single batched XLA program instead of one interpreter run per test.
"""

import pytest

from mythril_tpu.laser.conformance import VMTESTS_ROOT, load_vmtests, run_cases

if not VMTESTS_ROOT.is_dir():  # pragma: no cover
    pytest.skip("VMTests vectors not available", allow_module_level=True)

CASES, LOAD_SKIPS = load_vmtests()


@pytest.fixture(scope="module")
def verdicts():
    return run_cases(CASES)


@pytest.mark.parametrize("name", [c.name for c in CASES])
def test_vmtest(name, verdicts):
    v = verdicts[name]
    if v.startswith("skip"):
        pytest.skip(v)
    assert v == "pass", v


def test_coverage_floor(verdicts):
    """The batch engine must actually pass the bulk of the corpus —
    guards against silently skipping everything."""
    passed = sum(1 for v in verdicts.values() if v == "pass")
    assert passed >= 300, f"only {passed} VMTests passed"
