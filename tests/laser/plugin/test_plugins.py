"""Laser plugin behavior tests (reference test strategy:
tests/plugin/ + tests/laser/strategy/test_loop_bound.py)."""

import pytest

from mythril_tpu.laser.ethereum.strategy.basic import BreadthFirstSearchStrategy
from mythril_tpu.laser.ethereum.strategy.extensions.bounded_loops import (
    BoundedLoopsStrategy,
)
from mythril_tpu.laser.ethereum.svm import LaserEVM
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.plugin.builder import PluginBuilder
from mythril_tpu.laser.plugin.interface import LaserPlugin
from mythril_tpu.laser.plugin.loader import LaserPluginLoader
from mythril_tpu.laser.plugin.plugins.coverage.coverage_plugin import (
    InstructionCoveragePlugin,
)
from mythril_tpu.laser.plugin.plugins.mutation_pruner import MutationPruner


def wrap_runtime(runtime_hex: str) -> str:
    runtime = bytes.fromhex(runtime_hex)
    n = len(runtime)
    creation = bytes(
        [0x60, n, 0x60, 0x0C, 0x60, 0x00, 0x39, 0x60, n, 0x60, 0x00, 0xF3]
    )
    return (creation + runtime).hex()


def run(runtime_hex, plugins=(), tx_count=1, loop_bound=None):
    laser = LaserEVM(
        transaction_count=tx_count, execution_timeout=120, create_timeout=60
    )
    if loop_bound is not None:
        laser.extend_strategy(BoundedLoopsStrategy, loop_bound)
    for plugin in plugins:
        plugin.initialize(laser)
    laser.sym_exec(
        creation_code=wrap_runtime(runtime_hex),
        contract_name="T",
        world_state=WorldState(),
    )
    return laser


def test_coverage_plugin_records_executed_instructions():
    cov = InstructionCoveragePlugin()
    laser = run("6001600055600060015500", plugins=[cov])
    runtime_cov = [v for k, v in cov.coverage.items() if k == "6001600055600060015500"]
    assert runtime_cov
    total, mask = runtime_cov[0]
    assert sum(mask) == total  # straight-line code: everything covered


def test_mutation_pruner_drops_clean_transaction():
    # non-payable no-op: revert on callvalue != 0, else STOP. The STOP
    # path's constraints pin callvalue to 0, so the end state neither
    # mutates storage nor moves value and the pruner discards it.
    code = "34600557005b60006000fd"
    laser = run(code, plugins=[MutationPruner()])
    assert len(laser.open_states) == 0

    # without the pruner the open state survives
    laser2 = run(code)
    assert len(laser2.open_states) == 1


def test_mutation_pruner_keeps_mutating_transaction():
    laser = run("6001600055600060015500", plugins=[MutationPruner()])
    assert len(laser.open_states) == 1


def test_bounded_loops_strategy_terminates_infinite_loop():
    # JUMPDEST PUSH1 0 JUMP : tight infinite loop
    laser = run("5b600056", loop_bound=3)
    # finishes (pruned), leaving no open end states
    assert laser.total_states < 500


def test_plugin_loader_loads_and_deduplicates():
    loader = LaserPluginLoader()
    # fresh singleton state for this test
    loader.laser_plugin_builders = {}

    class DummyPlugin(LaserPlugin):
        initialized = 0

        def initialize(self, symbolic_vm):
            DummyPlugin.initialized += 1

    class DummyBuilder(PluginBuilder):
        plugin_name = "dummy"

        def __call__(self, *args, **kwargs):
            return DummyPlugin()

    builder = DummyBuilder()
    loader.load(builder)
    loader.load(builder)  # second load is a no-op
    assert list(loader.laser_plugin_builders) == ["dummy"]
    assert loader.is_enabled("dummy")

    laser = LaserEVM()
    loader.instrument_virtual_machine(laser, None)
    assert DummyPlugin.initialized == 1
    loader.laser_plugin_builders = {}
