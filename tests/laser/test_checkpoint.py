"""Checkpoint/resume roundtrip for the batched engine frontier."""

import numpy as np
import pytest

from mythril_tpu.laser.batch.checkpoint import load_checkpoint, save_checkpoint
from mythril_tpu.laser.batch.run import run
from mythril_tpu.laser.batch.state import make_batch, make_code_table


def demo():
    # PUSH1 1 PUSH1 0 SSTORE STOP
    code = make_code_table([bytes.fromhex("6001600055600060015500")])
    batch = make_batch(8, calldata=[b"\x00" * 4] * 8)
    return batch, code


def test_roundtrip(tmp_path):
    batch, code = demo()
    path = tmp_path / "ckpt.npz"
    save_checkpoint(path, batch, code, step=7)
    restored, code2, step = load_checkpoint(path)

    assert step == 7
    assert code2 is not None
    for name in batch._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(batch, name)), np.asarray(getattr(restored, name)),
            err_msg=name,
        )
    for name in code._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(code, name)), np.asarray(getattr(code2, name)),
            err_msg=name,
        )


def test_resume_continues_execution(tmp_path):
    batch, code = demo()
    # run 2 steps, checkpoint, then resume and run to completion
    mid, steps = run(batch, code, max_steps=2)
    path = tmp_path / "mid.npz"
    save_checkpoint(path, mid, code, step=int(steps))
    restored, code2, _ = load_checkpoint(path)

    done_direct, _ = run(mid, code, max_steps=64)
    done_resumed, _ = run(restored, code2, max_steps=64)
    np.testing.assert_array_equal(
        np.asarray(done_direct.status), np.asarray(done_resumed.status)
    )
    np.testing.assert_array_equal(
        np.asarray(done_direct.storage_vals), np.asarray(done_resumed.storage_vals)
    )


def test_version_guard(tmp_path):
    batch, code = demo()
    path = tmp_path / "ckpt.npz"
    save_checkpoint(path, batch, code)
    # corrupt the version
    import json

    data = dict(np.load(str(path)))
    data["meta"] = np.frombuffer(
        json.dumps({"version": 99}).encode(), dtype=np.uint8
    )
    np.savez_compressed(str(path), **data)
    with pytest.raises(ValueError):
        load_checkpoint(path)


def test_v1_checkpoint_zero_fills_new_fields(tmp_path):
    # v1 checkpoints predate pc_seen + the branch journal; loading one
    # must zero-fill those fields rather than reject the file
    import json

    batch, code = demo()
    path = tmp_path / "v1.npz"
    save_checkpoint(path, batch, code)
    data = dict(np.load(str(path)))
    for key in list(data):
        if key.split(".", 1)[-1] in ("pc_seen", "br_pc", "br_taken", "br_cnt"):
            del data[key]
    data["meta"] = np.frombuffer(
        json.dumps({"version": 1, "step": 0}).encode(), dtype=np.uint8
    )
    np.savez_compressed(str(path), **data)

    restored, code2, _ = load_checkpoint(path)
    assert int(np.asarray(restored.br_cnt).sum()) == 0
    done_a, _ = run(batch, code, max_steps=64)
    done_b, _ = run(restored, code2, max_steps=64)
    np.testing.assert_array_equal(
        np.asarray(done_a.status), np.asarray(done_b.status)
    )


def test_v2_checkpoint_defaults_empty_world(tmp_path):
    # v2 checkpoints predate the empty_world lane flag; loading one
    # must default it to the analyze world (all ones), not reject
    import json

    batch, code = demo()
    path = tmp_path / "v2.npz"
    save_checkpoint(path, batch, code)
    data = dict(np.load(str(path)))
    del data["batch.empty_world"]
    data["meta"] = np.frombuffer(
        json.dumps({"version": 2, "step": 0}).encode(), dtype=np.uint8
    )
    np.savez_compressed(str(path), **data)

    restored, code2, _ = load_checkpoint(path)
    assert np.asarray(restored.empty_world).tolist() == [1] * batch.n_lanes
    done_a, _ = run(batch, code, max_steps=64)
    done_b, _ = run(restored, code2, max_steps=64)
    np.testing.assert_array_equal(
        np.asarray(done_a.status), np.asarray(done_b.status)
    )


# -- arena-shape metadata (ISSUE 2 satellite) -------------------------------
def test_shape_metadata_written_and_readable(tmp_path):
    from mythril_tpu.laser.batch.checkpoint import arena_shape, checkpoint_shape

    batch, code = demo()
    path = tmp_path / "shaped.npz"
    save_checkpoint(path, batch, code, step=3)
    shape = checkpoint_shape(path)
    assert shape == arena_shape(batch, code)
    assert shape["lanes"] == 8
    assert shape["code_rows"] == 1


def test_mismatched_arena_shape_refuses_clearly(tmp_path):
    """An npz written by one arena shape must refuse to load into a
    mismatched one — a clear error naming the mismatch, not garbage
    lanes."""
    batch, code = demo()
    path = tmp_path / "narrow.npz"
    save_checkpoint(path, batch, code)
    with pytest.raises(ValueError, match="lanes: checkpoint has 8"):
        load_checkpoint(path, expect_shape={"lanes": 16})
    with pytest.raises(ValueError, match="mem_cap"):
        load_checkpoint(path, expect_shape={"lanes": 8, "mem_cap": 99})
    # the matching shape (and a partial expectation) load fine
    from mythril_tpu.laser.batch.checkpoint import arena_shape

    restored, _, _ = load_checkpoint(path, expect_shape=arena_shape(batch, code))
    np.testing.assert_array_equal(
        np.asarray(batch.pc), np.asarray(restored.pc)
    )
    load_checkpoint(path, expect_shape={"lanes": 8})


def test_replay_wave_refuses_mismatched_shape(tmp_path):
    from mythril_tpu.laser.batch.explore import replay_wave

    batch, code = demo()
    path = tmp_path / "wave.npz"
    save_checkpoint(path, batch, code, step=4)
    with pytest.raises(ValueError, match="arena shape"):
        replay_wave(str(path), expect_shape={"lanes": 512})


def test_pre_v4_checkpoint_shape_is_derived(tmp_path):
    """Checkpoints written before the shape metadata still refuse a
    mismatched load: the shape is derived from the stored arrays."""
    import json

    from mythril_tpu.laser.batch.checkpoint import checkpoint_shape

    batch, code = demo()
    path = tmp_path / "v3.npz"
    save_checkpoint(path, batch, code)
    data = dict(np.load(str(path)))
    data["meta"] = np.frombuffer(
        json.dumps({"version": 3, "step": 0}).encode(), dtype=np.uint8
    )
    np.savez_compressed(str(path), **data)
    shape = checkpoint_shape(path)
    assert shape["lanes"] == 8 and shape["code_rows"] == 1
    with pytest.raises(ValueError, match="arena shape"):
        load_checkpoint(path, expect_shape={"lanes": 4})
    restored, _, _ = load_checkpoint(path, expect_shape={"lanes": 8})
    assert restored.n_lanes == 8
