"""Per-contract specialized step kernels (ISSUE 6): opcode-set phase
pruning, superblock fusion, specialization buckets + the compile
cache, and the service CodeCache's kernel-slot eviction contract.

The acceptance bar: specialized and generic (--no-specialize) kernels
produce IDENTICAL issue sets on the fault-suite and the per-module
positive-fixture contracts, the pruning decisions and superblock
boundaries match goldens, a pruned opcode degrades to UNSUPPORTED
(never silent mis-execution), and evicting a service CodeCache entry
releases its compiled kernel. Everything runs on CPU JAX.
"""

import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mythril_tpu.laser.batch import specialize as sp
from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer
from mythril_tpu.laser.batch.run import run
from mythril_tpu.laser.batch.state import Status, make_batch, make_code_table
from mythril_tpu.laser.batch.step import PhaseSet
from mythril_tpu.laser.batch.symbolic import make_sym_batch, sym_run
from mythril_tpu.support.support_args import args as support_args

pytestmark = pytest.mark.specialize


@pytest.fixture(autouse=True)
def _specialization_on():
    """The suite tests the feature itself: re-enable the flag the test
    conftest turns off for tier-1 wall-time (see tests/conftest.py)."""
    before = support_args.specialize
    support_args.specialize = True
    yield
    support_args.specialize = before

#: the pipeline suite's fault-suite fixtures (same shapes, same seeds)
WRITER = "6001600055600060015500"
BRANCHER = "600035600757005b600160005500"
KILLABLE = "33ff"
GATED = "60003560f81c604214600d57005b600160005500"
#: a PUSH/DUP/SWAP-heavy straight line ending in a storage write — the
#: superblock-fusion showcase
PUSHY = "600160026003600450809101600055"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _module_fixture_codes():
    """The per-module positive-fixture bytecodes (every detection
    module's minimal firing contract), loaded from the fixture suite
    so the two lists can never drift apart."""
    path = os.path.join(
        _REPO, "tests", "analysis", "test_module_positive_fixtures.py"
    )
    spec = importlib.util.spec_from_file_location("_module_fixtures", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return [code for code, _swc in mod.FIXTURES.values()]


def _fingerprint(contract):
    return (
        tuple(map(tuple, contract["covered_branches"])),
        {
            kind: tuple(sorted(t["pc"] for t in bucket))
            for kind, bucket in contract["triggers"].items()
        },
        tuple(sorted((e["class"], e["pc"]) for e in contract["evidence"])),
    )


def _explore(codes, specialize, **kw):
    kw.setdefault("lanes_per_contract", 8)
    kw.setdefault("waves", 3)
    kw.setdefault("steps_per_wave", 64)
    kw.setdefault("transaction_count", 1)
    ex = DeviceCorpusExplorer(codes, specialize=specialize, **kw)
    return ex, ex.run()


# -- pruning decisions (goldens) ---------------------------------------------
def test_phase_decision_goldens():
    """The opcode-set pruning decisions for known bytecodes."""
    ph = sp.phases_for(sp.signature_for(bytes.fromhex(WRITER)))
    # PUSH/SSTORE/STOP only: everything else prunes
    assert ph.sstore and not ph.sload
    for flag in ("calls", "sha3", "mload", "mstore", "exp", "div",
                 "copy", "logs", "selfdestruct", "calldataload"):
        assert not getattr(ph, flag), flag

    ph = sp.phases_for(sp.signature_for(bytes.fromhex(GATED)))
    # CALLDATALOAD; SHR; EQ-compare; JUMPI; SSTORE
    assert ph.calldataload and ph.shifts and ph.cmp and ph.sstore
    assert not ph.calls and not ph.sha3 and not ph.arith

    ph = sp.phases_for(sp.signature_for(bytes.fromhex(KILLABLE)))
    assert ph.selfdestruct and ph.env_tx
    assert not ph.sstore

    # fusion is on by default and off on request
    assert ph.fuse_depth == sp.FUSE_DEPTH
    assert sp.phases_for(sp.signature_for(b"\x00"), fuse=False).fuse_depth == 0


def test_signature_prefers_static_summary_reachable_set():
    from mythril_tpu.analysis.static import analyze_bytecode

    # dead code after STOP carries a SHA3 the dispatcher never reaches
    code = bytes.fromhex("600160005500" + "6020600020")
    summary = analyze_bytecode(code)
    sig_static = sp.signature_for(code, summary)
    sig_sweep = sp.signature_for(code)
    assert "SHA3" in sig_sweep  # the linear sweep sees the dead tail
    if not summary.incomplete:
        assert "SHA3" not in sig_static  # the CFG proves it dead


def test_union_phases_covers_every_track():
    a = sp.phases_for(sp.signature_for(bytes.fromhex(WRITER)))
    b = sp.phases_for(sp.signature_for(bytes.fromhex(KILLABLE)))
    u = sp.union_phases([a, b])
    assert u.sstore and u.selfdestruct
    assert not u.sha3


# -- superblock boundaries (goldens) -----------------------------------------
def test_fuse_table_golden_marks_only_fusible_pcs():
    code = bytes.fromhex(WRITER)
    row = sp.build_fuse_row(code, 32)
    # PUSH1s at 0,2,5,7 are fusible; SSTOREs at 4,9 and STOP at 10 not
    expected = {0, 2, 5, 7}
    assert {int(i) for i in np.flatnonzero(row)} == expected
    # immediates are never marked (pc 1,3,6,8 are PUSH data)
    assert row[1] == 0 and row[3] == 0


def test_fuse_profitability_gate():
    """Fusion switches on only for run-dense code: the substep passes
    cost every iteration, so sparse-run contracts get pruning-only
    kernels (the production selection path passes this decision into
    phases_for)."""
    assert sp.fuse_profitable(bytes.fromhex(PUSHY))  # 8/10 ops in runs
    assert sp.fuse_profitable(bytes.fromhex(WRITER))  # paired PUSHes
    assert not sp.fuse_profitable(bytes.fromhex(KILLABLE))  # no runs
    assert not sp.fuse_profitable(b"")


def test_superblock_boundaries_golden():
    # PUSHY: PUSH1 x4, DUP1, SWAP2, SWAP1? -> one long run, then
    # PUSH1 0; SSTORE splits it
    runs = sp.fuse_run_lengths(bytes.fromhex(PUSHY))
    # run 1: four PUSH1s + DUP1 + SWAP2 + SWAP1 + ADD? — ADD (0x01) is
    # NOT fusible, so the first run ends before it
    assert runs[0][0] == 0 and runs[0][1] == 7
    # run 2: the PUSH1 0 before SSTORE
    assert runs[1] == (12, 1)


# -- kernel equivalence -------------------------------------------------------
#: ONE code set + ONE batch shape for both equivalence tests: the
#: concrete and sym legs then share a single specialization bucket
#: (the XLA compiles are the suite's wall cost)
_EQ_CODES = (WRITER, BRANCHER, KILLABLE, GATED, PUSHY)


def _eq_setup():
    codes = [bytes.fromhex(c) for c in _EQ_CODES]
    table = make_code_table(codes)
    fuse = jnp.asarray(
        sp.build_fuse_table(codes, table.ops.shape[1] - 33)
    )
    phases = sp.union_phases(
        [sp.phases_for(sp.signature_for(c)) for c in codes]
    )
    batch = make_batch(
        10, code_ids=[0, 1, 2, 3, 4] * 2, calldata=[b"\x42" * 8] * 10
    )
    return table, fuse, phases, batch


def test_specialized_concrete_kernel_matches_generic():
    table, fuse, phases, batch = _eq_setup()
    g_out, _ = run(batch, table, max_steps=64)
    kern = sp.kernel_cache().get(phases)
    s_out, _steps, fused, _blocks = kern.run(
        batch, table, fuse, max_steps=64
    )
    assert int(fused) > 0  # the fused substeps actually advanced work
    for i, (x, y) in enumerate(
        zip(jax.tree.flatten(g_out)[0], jax.tree.flatten(s_out)[0])
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), str(i))


def test_specialized_sym_kernel_matches_generic():
    table, fuse, phases, batch = _eq_setup()
    g_out, _s, _a = sym_run(make_sym_batch(batch), table, max_steps=64)
    kern = sp.kernel_cache().get(phases)
    s_out, _s2, _a2, fused, _blocks = kern.sym_run(
        make_sym_batch(batch), table, fuse, max_steps=64
    )
    assert int(fused) > 0
    for i, (x, y) in enumerate(
        zip(jax.tree.flatten(g_out)[0], jax.tree.flatten(s_out)[0])
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), str(i))


def test_pruned_opcode_degrades_to_unsupported_not_silent():
    """The safety net: a lane reaching an opcode whose phase the
    kernel pruned parks AT the instruction with UNSUPPORTED (host
    takeover) — it must never advance past it. (The kernel is WRITER's
    own tiny bucket with sstore flipped off — a near-generic bucket
    would pay a full-size compile for the same assertion.)"""
    code = bytes.fromhex(WRITER)
    table = make_code_table([code])
    batch = make_batch(2, calldata=[b""] * 2)
    wrong = sp.phases_for(sp.signature_for(code))._replace(sstore=False)
    out, _ = run(batch, table, max_steps=32, phases=wrong)
    assert (np.asarray(out.status) == Status.UNSUPPORTED).all()
    assert (np.asarray(out.pc) == 4).all()  # parked AT the SSTORE


# -- the explorer differential (acceptance criterion) ------------------------
def test_differential_issue_sets_fault_suite():
    codes = [KILLABLE, WRITER, BRANCHER, GATED]
    _, spec = _explore(codes, True, seed=7)
    _, generic = _explore(codes, False, seed=7)
    for s, g in zip(spec["contracts"], generic["contracts"]):
        assert _fingerprint(s) == _fingerprint(g)
    assert spec["stats"]["specialized"] == 1
    assert spec["stats"]["spec_pruned_phases"] > 0
    assert generic["stats"]["specialized"] == 0
    # and the differential is not trivially empty
    assert "selfdestruct" in spec["contracts"][0]["triggers"]


def test_differential_issue_sets_module_fixtures():
    """Every detection module's positive-fixture contract explores to
    the same coverage/trigger/evidence fingerprint under the
    specialized and the generic kernel."""
    codes = _module_fixture_codes()
    _, spec = _explore(codes, True, seed=11, waves=2)
    _, generic = _explore(codes, False, seed=11, waves=2)
    for s, g in zip(spec["contracts"], generic["contracts"]):
        assert _fingerprint(s) == _fingerprint(g)
    assert spec["stats"]["spec_fused_steps"] > 0


def test_no_specialize_flag_restores_generic_path():
    before = support_args.specialize
    try:
        support_args.specialize = False
        ex, out = _explore([WRITER], None)  # None -> read the flag bag
        assert ex._kernel is None
        assert out["stats"]["specialized"] == 0
    finally:
        support_args.specialize = before


# -- the compile cache --------------------------------------------------------
def test_kernel_cache_buckets_share_compiles():
    cache = sp.KernelCache(capacity=4)
    a = sp.phases_for(sp.signature_for(bytes.fromhex(WRITER)))
    b = sp.phases_for(sp.signature_for(bytes.fromhex(WRITER)))
    k1 = cache.get(a)
    k2 = cache.get(b)  # same bucket -> same kernel object
    assert k1 is k2
    assert cache.stats()["hits"] == 1 and cache.stats()["misses"] == 1


def test_kernel_cache_evicts_lru_and_keeps_pins():
    cache = sp.KernelCache(capacity=2)
    buckets = [
        PhaseSet(sha3=False),
        PhaseSet(exp=False),
        PhaseSet(div=False),
    ]
    pinned = cache.acquire(buckets[0])
    cache.get(buckets[1])
    cache.get(buckets[2])  # over capacity: evicts buckets[1], not the pin
    stats = cache.stats()
    assert stats["evictions"] == 1
    assert stats["pinned"] == 1
    assert cache.get(buckets[0]) is pinned  # survived as a hit
    # releasing the pin makes it evictable; an evicted pin drops NOW
    cache.release(pinned)
    cache.get(PhaseSet(modops=False))
    assert cache.stats()["size"] <= 2


def test_code_cache_eviction_releases_kernel():
    """The satellite fix: evicting a service CodeCache entry releases
    its pinned compiled kernel (previously only dense rows and static
    summaries were dropped — the kernel slot leaked)."""
    from mythril_tpu.service.engine import CodeCache

    cache = CodeCache(code_cap=64, capacity=1)
    spec1 = cache.spec_for(bytes.fromhex(WRITER))
    assert spec1 is not None and spec1["kernel"] is not None
    k1 = spec1["kernel"]
    refs_before = k1.refs
    # inserting a second code evicts the first entry -> pin released
    cache.spec_for(bytes.fromhex(KILLABLE))
    assert cache.evictions == 1
    assert cache.kernels_released == 1
    assert k1.refs == refs_before - 1


def test_code_cache_rebucket_releases_kernels():
    from mythril_tpu.service.engine import CodeCache

    cache = CodeCache(code_cap=64, capacity=4)
    assert cache.spec_for(bytes.fromhex(WRITER)) is not None
    assert cache.spec_for(bytes.fromhex(KILLABLE)) is not None
    pinned = cache.kernels_pinned
    cache.rebucket(128)
    assert cache.kernels_released == pinned


# -- the service warm path ----------------------------------------------------
def test_service_warm_waves_hit_kernel_cache():
    from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
    from mythril_tpu.service.jobs import Job

    engine = AnalysisEngine(
        ServiceConfig(
            stripes=2,
            lanes_per_stripe=4,
            steps_per_wave=64,
            max_waves=2,
            host_walk=False,
            coalesce_wait_s=0.05,
            idle_wait_s=0.02,
            # deterministic for the assertion: compile on the wave
            # instead of the production background warmup
            specialize_warmup="sync",
        )
    ).start()
    try:
        # two jobs of the SAME code: every wave's resident-set union is
        # one bucket, so the warm path is deterministic (and the suite
        # compiles one service kernel, not one per residency pattern)
        jobs = [engine.submit(Job(BRANCHER)), engine.submit(Job(BRANCHER))]
        for job in jobs:
            settled = engine.queue.wait_terminal(job.id, timeout_s=120.0)
            assert settled is not None and settled.state == "done", (
                settled.state if settled else "lost"
            )
        kernel = engine.stats()["kernel"]
        assert kernel["enabled"] is True
        assert kernel["spec_waves"] >= 1
        assert kernel["cache_hits"] >= 1  # warm waves rode the bucket
        assert kernel["fallbacks"] == 0
        assert kernel["pinned_codes"] >= 1
    finally:
        engine.close()


def test_service_no_specialize_runs_generic_waves():
    from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
    from mythril_tpu.service.jobs import Job

    engine = AnalysisEngine(
        ServiceConfig(
            stripes=1,
            lanes_per_stripe=4,
            steps_per_wave=64,
            max_waves=1,
            host_walk=False,
            coalesce_wait_s=0.05,
            idle_wait_s=0.02,
            specialize=False,
        )
    ).start()
    try:
        job = engine.submit(Job(WRITER))
        settled = engine.queue.wait_terminal(job.id, timeout_s=120.0)
        assert settled is not None and settled.state == "done"
        kernel = engine.stats()["kernel"]
        assert kernel["enabled"] is False
        assert kernel["spec_waves"] == 0
        assert kernel["generic_waves"] >= 1
    finally:
        engine.close()


def test_eviction_during_warmup_defers_drop_to_release():
    """ISSUE-17 satellite: capacity eviction racing a background
    warmup compile. The eviction may unmap the warmup-pinned entry
    (counted as an inflight eviction) but must NOT drop its
    executables under the compiling thread — the drop happens
    deterministically at release_warmup, when nothing else holds it."""
    cache = sp.KernelCache(capacity=1)
    k1 = cache.get(PhaseSet(sha3=False))
    cache.pin_warmup(k1)
    k2 = cache.get(PhaseSet(exp=False))  # over capacity: k1 unmapped
    stats = cache.stats()
    assert stats["evictions"] == 1
    assert stats["inflight_evictions"] == 1
    # unmapped, but the compiling thread's handle is still live
    assert k1._run is not None
    assert cache._entries.get(k1.phases) is not k1  # slot is gone for real
    # the warmup thread finishing is what frees the executables
    cache.release_warmup(k1)
    assert k1._run is None
    # k2 was never evicted: untouched by any of this
    assert k2._run is not None
    assert cache.stats()["inflight_evictions"] == 1


def test_warmup_pin_survives_when_not_evicted():
    """The re-pin half of the contract: a warmup pin on an entry that
    is NOT evicted leaves it mapped and live after release."""
    cache = sp.KernelCache(capacity=4)
    k1 = cache.get(PhaseSet(div=False))
    cache.pin_warmup(k1)
    cache.release_warmup(k1)
    assert k1.warm_refs == 0
    assert k1._run is not None
    assert cache.get(k1.phases) is k1


def test_inflight_eviction_bumps_registry_counter():
    from mythril_tpu.observe.registry import registry

    counter = registry().counter(
        "mtpu_kernel_cache_inflight_evictions_total",
        "buckets evicted while their background warmup compile was "
        "still in flight",
    )
    before = counter.value
    cache = sp.KernelCache(capacity=1)
    k1 = cache.pin_warmup(cache.get(PhaseSet(sha3=False)))
    cache.get(PhaseSet(exp=False))
    assert counter.value == before + 1
    cache.release_warmup(k1)
