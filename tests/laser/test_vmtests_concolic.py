"""Conformance: Ethereum VMTests replayed through the LASER engine's
concolic path (reference: tests/laser/evm_testsuite/evm_test.py:104-175).

This complements tests/laser/test_vmtests.py (which batches the same
corpus through the XLA interpreter): here every test runs the
object-model engine — Instruction handlers, MachineState gas bounds,
transaction plumbing — asserting post-storage/nonce/code equality and
the min-gas lower bound.
"""

from __future__ import annotations

import binascii
import json
from datetime import datetime
from pathlib import Path

import pytest

from mythril_tpu.disassembler.disassembly import Disassembly
from mythril_tpu.laser.conformance import VMTESTS_ROOT
from mythril_tpu.laser.ethereum.state.account import Account
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.ethereum.svm import LaserEVM
from mythril_tpu.laser.ethereum.time_handler import time_handler
from mythril_tpu.laser.ethereum.transaction.concolic import execute_message_call
from mythril_tpu.laser.smt import Expression, symbol_factory

if not VMTESTS_ROOT.is_dir():  # pragma: no cover
    pytest.skip("VMTests vectors not available", allow_module_level=True)

test_types = [
    "vmArithmeticTest",
    "vmBitwiseLogicOperation",
    "vmEnvironmentalInfo",
    "vmPushDupSwapTest",
    "vmTests",
    "vmSha3Test",
    "vmSystemOperations",
    "vmRandomTest",
    "vmIOandFlowOperations",
]

# same skip lists as the reference harness (evm_test.py:33-60) —
# minus its tests_with_block_number_support group: the concolic driver
# pins the environment's block number from the fixture env, so the
# NUMBER-derived dynamic jumps the reference must skip replay exactly
tests_with_gas_support = ["gas0", "gas1"]
tests_with_log_support = ["log1MemExp"]
tests_not_relevant = [
    "loop_stacklimit_1020",  # max_depth stops the loop before 1020
    "loop_stacklimit_1021",
]
# the reference also skips "jumpi_at_the_end" here; this engine passes
# it, so it stays enabled
tests_to_resolve = ["jumpTo1InstructionafterJump", "sstore_load_2"]
ignored_test_names = (
    tests_with_gas_support
    + tests_with_log_support
    + tests_not_relevant
    + tests_to_resolve
)


def load_test_data(designations):
    return_data = []
    for designation in designations:
        suite_dir = Path(VMTESTS_ROOT) / designation
        if not suite_dir.is_dir():
            continue
        for file_reference in suite_dir.iterdir():
            if file_reference.suffix != ".json":
                continue
            with file_reference.open() as file:
                top_level = json.load(file)
            for test_name, data in top_level.items():
                pre_condition = data["pre"]
                action = data["exec"]
                gas_before = int(action["gas"], 16)
                gas_after = data.get("gas")
                gas_used = (
                    gas_before - int(gas_after, 16) if gas_after is not None else None
                )
                post_condition = data.get("post", {})
                environment = data.get("env")
                return_data.append(
                    (
                        test_name,
                        environment,
                        pre_condition,
                        action,
                        gas_used,
                        post_condition,
                    )
                )
    return return_data


@pytest.mark.parametrize(
    "test_name, environment, pre_condition, action, gas_used, post_condition",
    load_test_data(test_types),
)
def test_vmtest_concolic(
    test_name: str,
    environment: dict,
    pre_condition: dict,
    action: dict,
    gas_used: int,
    post_condition: dict,
) -> None:
    if test_name in ignored_test_names:
        pytest.skip("reference-parity skip list")

    world_state = WorldState()
    for address, details in pre_condition.items():
        account = Account(address, concrete_storage=True)
        account.code = Disassembly(details["code"][2:])
        account.nonce = int(details["nonce"], 16)
        for key, value in details["storage"].items():
            key_bitvec = symbol_factory.BitVecVal(int(key, 16), 256)
            account.storage[key_bitvec] = symbol_factory.BitVecVal(
                int(value, 16), 256
            )
        world_state.put_account(account)
        account.set_balance(int(details["balance"], 16))

    time_handler.start_execution(10000)
    laser_evm = LaserEVM()
    laser_evm.open_states = [world_state]
    laser_evm.time = datetime.now()

    final_states = execute_message_call(
        laser_evm,
        callee_address=symbol_factory.BitVecVal(int(action["address"], 16), 256),
        caller_address=symbol_factory.BitVecVal(int(action["caller"], 16), 256),
        origin_address=symbol_factory.BitVecVal(int(action["origin"], 16), 256),
        code=action["code"][2:],
        gas_limit=int(action["gas"], 16),
        data=binascii.a2b_hex(action["data"][2:]),
        gas_price=int(action["gasPrice"], 16),
        value=int(action["value"], 16),
        track_gas=True,
        block_number=int((environment or {}).get("currentNumber", "0x0"), 16),
    )

    if gas_used is not None and gas_used < int(environment["currentGasLimit"], 16):
        gas_min_max = [
            (s.mstate.min_gas_used, s.mstate.max_gas_used) for s in final_states
        ]
        gas_ranges = [g[0] <= gas_used for g in gas_min_max]
        assert all(map(lambda g: g[0] <= g[1], gas_min_max))
        assert any(gas_ranges)

    if post_condition == {}:
        # an exceptional halt or OOG leaves no open world state
        assert len(laser_evm.open_states) == 0
    else:
        assert len(laser_evm.open_states) == 1
        world_state = laser_evm.open_states[0]
        for address, details in post_condition.items():
            account = world_state[symbol_factory.BitVecVal(int(address, 16), 256)]
            assert account.nonce == int(details["nonce"], 16)
            assert account.code.bytecode == details["code"][2:]

            for index, value in details["storage"].items():
                expected = int(value, 16)
                actual = account.storage[
                    symbol_factory.BitVecVal(int(index, 16), 256)
                ]
                if isinstance(actual, Expression):
                    actual = actual.value
                    actual = 1 if actual is True else 0 if actual is False else actual
                else:
                    if type(actual) == bytes:
                        actual = int(binascii.b2a_hex(actual), 16)
                    elif type(actual) == str:
                        actual = int(actual, 16)
                assert actual == expected, f"storage[{index}]"
