"""Stack-capacity semantics: the EVM allows depth 1024; the device
model often runs a smaller cap for bandwidth. Outgrowing a sub-1024
MODEL cap must degrade the lane to the host (UNSUPPORTED — capacity,
not behavior), while crossing the true EVM limit with a full-size
stack is the genuine stack error. Reference anchor: the
StackOverflowException at mythril/laser/ethereum/machine_state.py."""

import numpy as np
import pytest

from mythril_tpu.laser.batch.run import run
from mythril_tpu.laser.batch.state import Status, make_batch, make_code_table


def _pusher(n_pushes: int) -> bytes:
    # PUSH1 1, n times, then STOP
    return bytes([0x60, 0x01] * n_pushes + [0x00])


def _run(code: bytes, stack_cap: int):
    table = make_code_table([code])
    batch = make_batch(
        4, calldata=[b""] * 4, stack_cap=stack_cap
    )
    out, _ = run(batch, table, max_steps=4096)
    return np.asarray(out.status)


def test_small_cap_overflow_degrades_not_errors():
    status = _run(_pusher(200), stack_cap=128)
    assert (status == Status.UNSUPPORTED).all(), status


def test_full_cap_runs_deep_contract():
    status = _run(_pusher(200), stack_cap=1024)
    assert (status == Status.STOPPED).all(), status


@pytest.mark.slow
def test_true_evm_limit_is_a_stack_error():
    status = _run(_pusher(1100), stack_cap=1024)
    assert (status == Status.ERR_STACK).all(), status


def test_shallow_contract_unaffected_by_cap():
    status = _run(_pusher(10), stack_cap=128)
    assert (status == Status.STOPPED).all(), status
