"""Step-kernel compilation budget (VERDICT r3 #8).

The tunneled TPU link charges a fixed ~ms dispatch floor per compiled
segment inside the jit'd while loop (docs/roadmap.md "Performance
findings"), so the segment census IS the kernel's cost model: a change
that doubles the fusion count halves corpus wave throughput even if
every op is cheap. The round-3 census existed only as a roadmap note;
this pins it in CI.

Counts are taken on the CPU backend (tests run on the virtual mesh),
whose absolute numbers differ from the TPU compile — the budget is a
REGRESSION tripwire for structural bloat (new unfused segments, phase
conditionals, concat custom-calls), not a cross-backend constant. On
a budget trip: either fuse the regression away or re-measure and bump
the budget in the same commit that justifies it.
"""

import jax
import pytest

from __graft_entry__ import _demo_workload
from mythril_tpu.laser.batch.step import step

#: measured on the CPU backend 2026-07-31: 1097 fusion instructions
#: and 18 conditionals across the compiled step module (the TPU
#: compile of the same kernel measured 75 fusions / 11 conditionals in
#: its while body — backends fuse differently; this budget tracks the
#: CPU number CI can see). ~25% headroom for benign drift.
FUSION_BUDGET = 1370
CONDITIONAL_BUDGET = 24


@pytest.fixture(scope="module")
def step_hlo():
    batch, code = _demo_workload(n_lanes=64)
    return jax.jit(step).lower(batch, code).compile().as_text()


def test_fusion_count_within_budget(step_hlo):
    # "fusion(" appears exactly once per fusion instruction definition
    # (references are bare %fusion.N, no parenthesis)
    n = step_hlo.count("fusion(")
    assert 0 < n <= FUSION_BUDGET, (
        f"step kernel compiles to {n} fusions (budget {FUSION_BUDGET}) — "
        "a segment regression multiplies the per-step dispatch floor"
    )


def test_conditional_count_within_budget(step_hlo):
    n = step_hlo.count(" conditional(")
    assert 0 < n <= CONDITIONAL_BUDGET, (
        f"step kernel compiles to {n} conditionals "
        f"(budget {CONDITIONAL_BUDGET}); phase gates multiply segments"
    )
