"""The fault-injection suite: deadline supervision, solver/device
escalation ladders, and checkpointed graceful degradation.

Every fault here is DETERMINISTIC — armed at a named injection site the
production code reaches (tests/laser/faultinject.py), never a timing
race. The acceptance bar (ISSUE 1): an injected solver hang, an
injected device dispatch failure, and a mid-run SIGTERM each produce a
completed run with a partial-but-well-formed result (no traceback,
findings preserved, degradation reasons recorded), and a killed wave
resumes from its npz checkpoint to the uninterrupted run's results.
"""

import json

import numpy as np
import pytest

from mythril_tpu.exceptions import (
    DeadlineExpiredError,
    DeviceDispatchError,
    WatchdogTimeout,
)
from mythril_tpu.laser.batch.checkpoint import load_checkpoint, save_checkpoint
from mythril_tpu.laser.batch.run import run, run_resilient
from mythril_tpu.laser.batch.state import make_batch, make_code_table
from mythril_tpu.support import resilience

# tests/laser is not a package: pytest's rootdir import mode puts this
# directory on sys.path, so the harness imports flat
from faultinject import device_faults, sigterm_at, solver_hang  # noqa: E402

pytestmark = pytest.mark.faults

#: PUSH1 1; PUSH1 0; SSTORE; PUSH1 0; PUSH1 1; SSTORE; STOP
WRITER = "6001600055600060015500"
#: CALLDATALOAD(0) branches to a storage write — one symbolic JUMPI,
#: so waves have a branch journal to checkpoint/replay
BRANCHER = "600035600757005b600160005500"
#: SELFDESTRUCT — banks trigger evidence in one wave
KILLABLE = "33ff"


@pytest.fixture(autouse=True)
def _clean_supervisor():
    """Every test starts from a quiet supervisor: no armed faults, no
    run deadline, no pending shutdown, empty degradation log."""
    resilience.disarm_faults()
    resilience.clear_run_deadline()
    resilience.clear_shutdown()
    resilience.DegradationLog().reset()
    yield
    resilience.disarm_faults()
    resilience.clear_run_deadline()
    resilience.clear_shutdown()


# -- primitives -------------------------------------------------------------
def test_deadline_clamp_and_expiry():
    dl = resilience.Deadline(30.0)
    assert not dl.expired
    assert dl.clamp_ms(10_000) <= 10_000
    spent = resilience.Deadline(0.0)
    assert spent.expired
    # a nearly-expired run still gives queries the floor, never zero
    assert spent.clamp_ms(10_000) == 200
    with pytest.raises(DeadlineExpiredError):
        spent.check("test")
    assert resilience.Deadline(None).clamp_ms(7_000) == 7_000


def test_retry_policy_backoff_schedule():
    policy = resilience.RetryPolicy(
        attempts=5, base_delay_s=0.1, multiplier=2.0, max_delay_s=0.3
    )
    assert policy.delays() == [0.1, 0.2, 0.3, 0.3]


def test_degradation_log_counts_and_marker():
    log = resilience.DegradationLog()
    marker = log.marker()
    log.record(resilience.DegradationReason.SOLVER_HANG, site="t")
    log.record(resilience.DegradationReason.SOLVER_HANG, site="t")
    delta = log.counts_since(marker)
    assert delta == {"solver-hang": 2}
    assert log.events[-1]["site"] == "t"


def test_graceful_shutdown_nesting_preserves_signal():
    """An inner scope's exit must not erase a signal the outer loop
    still needs to honor."""
    with resilience.graceful_shutdown():
        with resilience.graceful_shutdown():
            resilience.shutdown_event().set()
        assert resilience.shutdown_requested()
    assert not resilience.shutdown_requested()  # outermost exit clears


# -- device-dispatch escalation ladder --------------------------------------
def _demo():
    code = make_code_table([bytes.fromhex(WRITER)])
    return make_batch(8, calldata=[b"\x00" * 4] * 8), code


def test_injected_device_fault_is_retried():
    batch, code = _demo()
    reference, _ = run(batch, code, max_steps=64)
    with device_faults(times=1):
        out, _ = run_resilient(batch, code, max_steps=64)
    np.testing.assert_array_equal(
        np.asarray(out.status), np.asarray(reference.status)
    )
    counts = resilience.DegradationLog().counts
    assert counts.get("device-dispatch-failed") == 1


def test_persistent_fault_falls_back_to_split_dispatch():
    """Full-batch dispatches keep dying; the ladder degrades to two
    half-sized dispatches and the merged result is bit-identical."""
    batch, code = _demo()
    reference, _ = run(batch, code, max_steps=64)
    with device_faults(times=3):  # all 3 full-batch attempts die
        out, _ = run_resilient(batch, code, max_steps=64)
    np.testing.assert_array_equal(
        np.asarray(out.status), np.asarray(reference.status)
    )
    np.testing.assert_array_equal(
        np.asarray(out.storage_vals), np.asarray(reference.storage_vals)
    )
    counts = resilience.DegradationLog().counts
    assert counts.get("device-split-dispatch") == 1


def test_dispatch_exhaustion_raises_for_the_caller_to_degrade():
    batch, code = _demo()
    with device_faults(times=99):
        with pytest.raises(DeviceDispatchError):
            run_resilient(batch, code, max_steps=64, retries=1)


def test_genuine_bugs_do_not_enter_the_ladder():
    """Only classified infrastructure faults retry; a logic error
    propagates with its traceback intact."""
    with pytest.raises(TypeError):
        resilience.retry_device_dispatch(
            lambda: (_ for _ in ()).throw(TypeError("shape bug")),
            label="test",
        )
    assert not resilience.DegradationLog().counts


# -- checkpointed graceful degradation --------------------------------------
def test_checkpoint_resume_after_killed_wave(tmp_path):
    """A wave killed mid-run resumes from the flushed npz to results
    identical to an uninterrupted run (the determinism DTVM's argument
    needs from interrupted runs)."""
    batch, code = _demo()
    mid, steps = run(batch, code, max_steps=2)
    flush = tmp_path / "flush.npz"
    save_checkpoint(flush, mid, code, step=int(steps))
    # the next wave dies past the whole ladder (split disabled to model
    # a dead device rather than an OOM)
    with device_faults(times=99):
        with pytest.raises(DeviceDispatchError):
            run_resilient(mid, code, max_steps=64, retries=1, allow_split=False)
    # "new process": resume from disk, run to completion
    restored, code2, _ = load_checkpoint(flush)
    resumed, _ = run_resilient(restored, code2, max_steps=64)
    direct, _ = run(mid, code, max_steps=64)
    np.testing.assert_array_equal(
        np.asarray(resumed.status), np.asarray(direct.status)
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.storage_vals), np.asarray(direct.storage_vals)
    )


def test_wave_checkpoint_replay_matches_explorer_coverage(tmp_path):
    """The explorer flushes every wave's seeded frontier before
    dispatch; replaying the flushed wave reproduces the exact branch
    coverage the live wave harvested."""
    from mythril_tpu.laser.batch.explore import (
        DeviceCorpusExplorer,
        replay_wave,
    )

    path = str(tmp_path / "wave.npz")
    ex = DeviceCorpusExplorer(
        [BRANCHER],
        lanes_per_contract=8,
        waves=1,
        steps_per_wave=64,
        transaction_count=1,
        checkpoint_path=path,
    )
    out = ex.run()
    assert out["stats"]["wave_checkpoints"] == 1
    covered = {tuple(b) for b in out["contracts"][0]["covered_branches"]}
    assert covered, "the branching fixture must cover at least one direction"

    view, _sym_out, _steps = replay_wave(path)
    replayed = set()
    for lane in range(8):
        for pc, taken, _tid in view.journal(lane):
            replayed.add((pc, taken))
    assert replayed == covered


def test_wave_fault_degrades_exploration_not_the_run():
    """A wave dispatch that dies past the retry ladder ends the
    exploration with partial outcomes — ownership gates open, evidence
    intact — instead of raising out of run()."""
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

    with device_faults(times=10):
        ex = DeviceCorpusExplorer(
            [WRITER],
            lanes_per_contract=8,
            waves=2,
            steps_per_wave=64,
            transaction_count=1,
        )
        out = ex.run()
    assert out["stats"]["device_faults"] == 1
    assert not out["contracts"][0]["device_complete"]
    counts = resilience.DegradationLog().counts
    assert counts.get("wave-abandoned") == 1


def test_explorer_deadline_stops_at_wave_boundary():
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

    ex = DeviceCorpusExplorer(
        [WRITER],
        lanes_per_contract=8,
        waves=4,
        steps_per_wave=64,
        transaction_count=2,
        deadline=resilience.Deadline(0.0),
    )
    out = ex.run()
    assert out["stats"]["waves"] == 0
    assert out["stats"]["halt_reason"] == "deadline-expired"
    assert not out["contracts"][0]["device_complete"]


# -- solver escalation ladder -----------------------------------------------
def test_solver_hang_watchdog_rebuilds_and_retries():
    """A wedged native CDCL call is abandoned by the watchdog, the
    clause session rebuilt, and the query retried — the answer still
    comes back sat, with the hang recorded as a degradation reason."""
    from mythril_tpu.laser.smt import terms
    from mythril_tpu.laser.smt.solver.solver import check_terms

    x = terms.bv_var("fault_x", 8)
    y = terms.bv_var("fault_y", 8)
    query = [terms.ult(x, y), terms.ult(terms.bv_const(3, 8), x)]
    with solver_hang(delay_s=2.0, grace_s=0.2, times=1):
        verdict, model = check_terms(query, timeout_ms=300)
    assert verdict == "sat"
    xv = model.assignment["fault_x"]
    yv = model.assignment["fault_y"]
    assert 3 < xv < yv
    counts = resilience.DegradationLog().counts
    assert counts.get("solver-hang") == 1
    assert counts.get("solver-session-rebuilt") == 1


def test_solver_double_hang_degrades_to_unknown():
    """Both the original attempt and the post-rebuild retry wedge: the
    query degrades to UNKNOWN-with-reason instead of hanging the run."""
    from mythril_tpu.laser.smt import terms
    from mythril_tpu.laser.smt.solver.solver import check_terms

    x = terms.bv_var("fault2_x", 8)
    y = terms.bv_var("fault2_y", 8)
    query = [terms.ult(x, y), terms.ult(terms.bv_const(5, 8), x)]
    with solver_hang(delay_s=2.0, grace_s=0.15, times=99):
        verdict, model = check_terms(query, timeout_ms=200)
    assert verdict == "unknown"
    assert model is None
    counts = resilience.DegradationLog().counts
    assert counts.get("solver-hang", 0) >= 2
    # and the rebuilt session still answers once the fault clears
    from mythril_tpu.laser.smt.solver.solver import check_terms as ct

    verdict, _ = ct(query, timeout_ms=2000)
    assert verdict == "sat"


def test_watchdog_abandon_leaks_never_frees():
    """close() on an abandoned session must not free the native object
    out from under a zombie thread."""
    from mythril_tpu.laser.smt.solver import native_sat

    session = native_sat.SolverSession()
    session.abandon()
    session.close()  # must be a no-op, not a use-after-free
    assert session.poisoned and session.abandoned


def test_expired_run_deadline_degrades_queries():
    from mythril_tpu.laser.smt import terms
    from mythril_tpu.laser.smt.solver.solver import check_terms

    resilience.set_run_deadline(0.0)
    x = terms.bv_var("fault3_x", 8)
    verdict, model = check_terms(
        [terms.ult(x, terms.bv_const(9, 8))], timeout_ms=5_000
    )
    assert verdict == "unknown" and model is None
    assert resilience.DegradationLog().counts.get("solver-timeout", 0) >= 1


def test_independence_solver_respects_run_deadline():
    from mythril_tpu.laser.smt import symbol_factory
    from mythril_tpu.laser.smt.solver.independence_solver import (
        IndependenceSolver,
    )

    a = symbol_factory.BitVecSym("fault4_a", 8)
    solver = IndependenceSolver(timeout=5_000)
    solver.add(a > symbol_factory.BitVecVal(3, 8))
    resilience.set_run_deadline(0.0)
    assert solver.check() == "unknown"


# -- corpus supervision -----------------------------------------------------
CORPUS = [(KILLABLE, "", f"K{i}") for i in range(4)]


def test_expired_deadline_yields_partial_shaped_results():
    from mythril_tpu.analysis.corpus import analyze_corpus

    results = analyze_corpus(
        CORPUS,
        transaction_count=1,
        execution_timeout=5,
        processes=1,
        use_device=False,
        deadline_s=0.0,
    )
    assert len(results) == len(CORPUS)
    for result in results:
        assert result["skipped"] == "deadline-expired"
        assert result["complete"] is False
        assert result["error"] is None
        json.dumps(result)  # well-formed: serializes clean


def test_on_timeout_fail_raises():
    from mythril_tpu.analysis.corpus import analyze_corpus

    with pytest.raises(DeadlineExpiredError):
        analyze_corpus(
            CORPUS,
            transaction_count=1,
            execution_timeout=5,
            processes=1,
            use_device=False,
            deadline_s=0.0,
            on_timeout="fail",
        )


def test_midrun_sigterm_keeps_findings_and_marks_the_tail():
    """SIGTERM lands at the third contract boundary: the first two
    keep their findings, the rest are marked skipped with the
    structured reason — a completed run, not a traceback."""
    from mythril_tpu.analysis.corpus import analyze_corpus

    with resilience.graceful_shutdown():
        with sigterm_at("corpus.contract", skip=2):
            results = analyze_corpus(
                CORPUS,
                transaction_count=1,
                execution_timeout=10,
                processes=1,
                use_device=False,
            )
    assert len(results) == len(CORPUS)
    assert results[0]["complete"] and results[0]["issues"]
    assert results[1]["complete"]
    for result in results[2:]:
        assert result["skipped"] == "interrupted"
        assert result["error"] is None
    counts = resilience.DegradationLog().counts
    assert counts.get("interrupted") == 1
    assert counts.get("contract-skipped") == 2


def test_device_fault_degrades_one_lane_not_the_corpus():
    """The acceptance scenario: every device dispatch dies, and the
    corpus still completes on the host with findings and recorded
    degradation — the chip failing degrades the device AXIS, never the
    service. One contract forces the SYNCHRONOUS prepass branch, so
    the injected fault deterministically hits the wave dispatch before
    any host analysis can finish first."""
    from mythril_tpu.analysis.corpus import analyze_corpus

    # PUSH1 0; CALLDATALOAD; POP; CALLER; SELFDESTRUCT — long enough
    # for the device prepass to stripe, and the host walk reports the
    # unprotected selfdestruct
    contracts = [("6000355033ff", "", "DevKill")]
    with device_faults(times=99):
        results = analyze_corpus(
            contracts,
            transaction_count=1,
            execution_timeout=10,
            processes=1,
            use_device=True,
        )
    assert len(results) == 1
    result = results[0]
    assert result["complete"], result
    assert result["error"] is None
    assert not result.get("owned")
    assert result["issues"], "host walk findings preserved"
    counts = resilience.DegradationLog().counts
    assert counts.get("device-dispatch-failed", 0) >= 1
    assert counts.get("wave-abandoned", 0) >= 1


# -- report surfacing -------------------------------------------------------
def test_report_renders_degradation_only_when_present():
    from mythril_tpu.analysis.report import Report

    clean = Report()
    assert "degradation" not in json.loads(clean.as_json())

    report = Report()
    report.partial = True
    report.degradation = {
        "reasons": {"deadline-expired": 1, "contract-skipped": 2},
        "contracts": [
            {"contract": "A", "complete": True, "device_complete": True},
            {"contract": "B", "complete": False, "skipped": "deadline-expired"},
        ],
    }
    as_json = json.loads(report.as_json())
    assert as_json["partial"] is True
    assert as_json["degradation"]["reasons"]["contract-skipped"] == 2
    jsonv2 = json.loads(report.as_swc_standard_format())
    meta = jsonv2[0]["meta"]
    assert meta["partial"] is True
    assert meta["degradation"]["contracts"][1]["complete"] is False


# -- split-ladder kwarg propagation (ISSUE 2 satellite) ---------------------
def test_split_retry_preserves_unroll_and_coverage_kwargs():
    """The retry->split ladder must thread the caller's exact kwargs:
    a split that silently reset `unroll`/`track_coverage` to defaults
    would change coverage accounting (pc_seen suddenly populated) and
    step bookkeeping (odd step counts) mid-escalation."""
    batch, code = _demo()
    # all 3 full-batch attempts die; the 4-lane halves succeed
    with device_faults(times=3):
        out, steps = run_resilient(
            batch, code, max_steps=64, unroll=2, track_coverage=False
        )
    counts = resilience.DegradationLog().counts
    assert counts.get("device-split-dispatch") == 1
    # the WRITER fixture halts cleanly on every lane
    assert set(np.asarray(out.status).tolist()) == {1}  # Status.STOPPED
    # track_coverage=False survived the split: no lane banked coverage
    assert int(np.asarray(out.pc_seen).sum()) == 0
    # unroll=2 survived the split: WRITER is 7 instructions, so the
    # unrolled loop lands on 8 (7 with the default unroll=1)
    assert int(steps) == 8


def test_recursive_split_descends_with_kwargs_until_single_lane():
    """Persistent faults keep splitting (8 -> 4 -> 2 -> 1) with the
    kwargs intact at every rung, and only a single lane's failure
    raises for the caller to degrade."""
    batch, code = _demo()
    with device_faults(times=999):
        with pytest.raises(DeviceDispatchError):
            run_resilient(
                batch, code, max_steps=64, unroll=2,
                track_coverage=False, retries=0,
            )
    counts = resilience.DegradationLog().counts
    # one split per level of the 8-lane descent
    assert counts.get("device-split-dispatch", 0) >= 3


# -- embeddable signal handlers (ISSUE 2 satellite) -------------------------
def test_supervisor_handler_chains_to_embedding_server():
    """A server that installed its own drain handler BEFORE the
    supervisor keeps receiving the signal: the supervisor's handler
    sets the shutdown event and then chains."""
    import os
    import signal

    import time

    delivered = []

    def embedder_handler(signum, frame):
        delivered.append(signum)

    previous = signal.signal(signal.SIGTERM, embedder_handler)
    try:
        with resilience.graceful_shutdown():
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(200):  # delivery is next-bytecode, not instant
                if resilience.shutdown_requested():
                    break
                time.sleep(0.005)
            assert resilience.shutdown_requested()
            assert delivered == [signal.SIGTERM]
        # exit restored the embedder's handler, not SIG_DFL
        assert signal.getsignal(signal.SIGTERM) is embedder_handler
        assert delivered == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, previous)


def test_supervisor_install_is_idempotent_across_repeated_runs():
    """Repeated supervised runs under an embedding server's handler:
    every exit restores the embedder's handler, and the supervisor can
    never save ITSELF as the previous handler (the clobbering leak the
    satellite fixes)."""
    import signal

    def embedder(signum, frame):
        pass

    previous = signal.signal(signal.SIGTERM, embedder)
    try:
        for _ in range(3):
            with resilience.graceful_shutdown():
                assert (
                    signal.getsignal(signal.SIGTERM)
                    is resilience._supervisor_handler
                )
            assert signal.getsignal(signal.SIGTERM) is embedder
        # even if the supervisor's handler is already installed when a
        # scope enters, it must not become its own "previous"
        signal.signal(signal.SIGTERM, resilience._supervisor_handler)
        with resilience.graceful_shutdown():
            pass
        assert (
            signal.getsignal(signal.SIGTERM)
            is resilience._supervisor_handler
        )
        assert (
            resilience._PREVIOUS_HANDLERS.get(signal.SIGTERM)
            is not resilience._supervisor_handler
        )
    finally:
        signal.signal(signal.SIGTERM, previous)


def test_supervisor_exit_respects_midrun_reregistration():
    """An embedder that re-registers its own handler DURING a
    supervised run keeps it: exit only restores when the installed
    handler is still the supervisor's."""
    import signal

    def late_embedder(signum, frame):
        pass

    original = signal.getsignal(signal.SIGTERM)
    try:
        with resilience.graceful_shutdown():
            signal.signal(signal.SIGTERM, late_embedder)
        assert signal.getsignal(signal.SIGTERM) is late_embedder
    finally:
        signal.signal(signal.SIGTERM, original)
