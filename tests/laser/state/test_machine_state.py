"""State-model unit tests (reference test strategy: tests/laser/state/
mstack/mstate tests — SURVEY.md §4)."""

import pytest

from mythril_tpu.laser.ethereum.evm_exceptions import (
    StackOverflowException,
    StackUnderflowException,
)
from mythril_tpu.laser.ethereum.state.machine_state import MachineStack, MachineState
from mythril_tpu.laser.smt import symbol_factory


def test_stack_append_converts_ints():
    stack = MachineStack()
    stack.append(5)
    assert stack[0].value == 5
    assert stack[0].size() == 256


def test_stack_overflow():
    stack = MachineStack()
    for i in range(1024):
        stack.append(i)
    with pytest.raises(StackOverflowException):
        stack.append(1)


def test_stack_underflow():
    with pytest.raises(StackUnderflowException):
        MachineStack().pop()


def test_stack_no_concat():
    with pytest.raises(NotImplementedError):
        MachineStack([symbol_factory.BitVecVal(0, 256)]) + MachineStack()


def test_mstate_pop_order():
    state = MachineState(gas_limit=8000000)
    for v in (1, 2, 3):
        state.stack.append(v)
    a, b = state.pop(2)
    assert (a.value, b.value) == (3, 2)
    assert state.pop().value == 1


def test_memory_gas_quadratic():
    state = MachineState(gas_limit=8000000)
    # growing to 32 words costs 3*32 + 32*32/512 = 98
    assert state.calculate_memory_gas(0, 1024) == 3 * 32 + (32 * 32) // 512


def test_mem_extend_rounds_to_words():
    state = MachineState(gas_limit=8000000)
    state.mem_extend(0, 33)
    assert state.memory_size == 64


def test_memory_word_roundtrip():
    state = MachineState(gas_limit=8000000)
    state.mem_extend(0, 32)
    state.memory.write_word_at(0, 0xDEADBEEF)
    assert state.memory.get_word_at(0) == 0xDEADBEEF


def test_memory_symbolic_word_roundtrip():
    state = MachineState(gas_limit=8000000)
    state.mem_extend(0, 32)
    x = symbol_factory.BitVecSym("x", 256)
    state.memory.write_word_at(0, x)
    assert (state.memory.get_word_at(0) == x).value is True
