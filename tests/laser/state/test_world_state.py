"""WorldState / Account / Storage tests (reference:
tests/laser/state/storage_test.py, world_state_account_exist_load)."""

from copy import copy

from mythril_tpu.laser.ethereum.state.account import Account, Storage
from mythril_tpu.laser.ethereum.state.world_state import (
    WorldState,
    generate_contract_address,
)
from mythril_tpu.laser.smt import symbol_factory


def test_concrete_storage_defaults_zero():
    s = Storage(concrete=True)
    assert s[symbol_factory.BitVecVal(1, 256)].value == 0


def test_symbolic_storage_roundtrip():
    s = Storage(concrete=False, address=symbol_factory.BitVecVal(0xAA, 256))
    key = symbol_factory.BitVecVal(1, 256)
    s[key] = symbol_factory.BitVecVal(77, 256)
    assert s[key].value == 77


def test_storage_copy_isolated():
    s = Storage(concrete=True)
    key = symbol_factory.BitVecVal(1, 256)
    s[key] = symbol_factory.BitVecVal(1, 256)
    s2 = copy(s)
    s2[key] = symbol_factory.BitVecVal(2, 256)
    assert s[key].value == 1
    assert s2[key].value == 2


def test_world_state_autocreate_account():
    ws = WorldState()
    acc = ws[symbol_factory.BitVecVal(0xDEAD, 256)]
    assert acc.address.value == 0xDEAD
    assert 0xDEAD in ws.accounts


def test_world_state_copy_isolates_storage():
    ws = WorldState()
    acc = ws.create_account(balance=10, address=0xAA, concrete_storage=True)
    key = symbol_factory.BitVecVal(0, 256)
    acc.storage[key] = symbol_factory.BitVecVal(5, 256)
    ws2 = copy(ws)
    ws2.accounts[0xAA].storage[key] = symbol_factory.BitVecVal(9, 256)
    assert ws.accounts[0xAA].storage[key].value == 5
    assert ws2.accounts[0xAA].storage[key].value == 9


def test_balance_through_shared_array():
    ws = WorldState()
    acc = ws.create_account(balance=100, address=0xBB)
    assert acc.balance().value == 100
    acc.add_balance(50)
    assert acc.balance().value == 150


def test_create_address_matches_known_vector():
    # well-known vector: sender 0x6ac7ea33f8831ea9dcc53393aaa88b25a785dbf0, nonce 0
    # -> 0xcd234a471b72ba2f1ccf0a70fcaba648a5eecd8d (the "cryptokitties" example)
    addr = generate_contract_address(0x6AC7EA33F8831EA9DCC53393AAA88B25A785DBF0, 0)
    assert addr == 0xCD234A471B72BA2F1CCF0A70FCABA648A5EECD8D
