"""Calldata model tests (reference: tests/laser/state/calldata_test)."""

import pytest

from mythril_tpu.laser.ethereum.state.calldata import (
    BasicConcreteCalldata,
    BasicSymbolicCalldata,
    ConcreteCalldata,
    SymbolicCalldata,
)
from mythril_tpu.laser.smt import symbol_factory
from mythril_tpu.laser.smt.solver import Solver, sat


@pytest.mark.parametrize("cls", [ConcreteCalldata, BasicConcreteCalldata])
def test_concrete_load(cls):
    cd = cls(0, [1, 2, 3, 4])
    assert cd[1].value == 2 if hasattr(cd[1], "value") else cd[1] == 2
    assert cd.calldatasize.value == 4


def test_concrete_word(monkeypatch):
    cd = ConcreteCalldata(0, list(range(32)))
    word = cd.get_word_at(0)
    expected = int.from_bytes(bytes(range(32)), "big")
    assert word.value == expected


def test_concrete_out_of_bounds_zero():
    cd = ConcreteCalldata(0, [1, 2])
    assert cd[10].value == 0


def test_symbolic_calldata_oob_is_zero():
    cd = SymbolicCalldata("2")
    # idx >= size must read zero: size==0 forces cd[5]==0
    s = Solver()
    s.add(cd.calldatasize == 0)
    value = cd[5]
    s.add(value == 0)
    assert s.check() == sat


def test_symbolic_calldata_constrainable():
    cd = SymbolicCalldata("2")
    value = cd[1]
    s = Solver()
    s.add(cd.calldatasize == 10)
    s.add(value == 0x42)
    assert s.check() == sat
    model = s.model()
    assert model.eval_int(value) == 0x42


def test_basic_symbolic_reads_consistent():
    cd = BasicSymbolicCalldata("3")
    idx = symbol_factory.BitVecVal(1, 256)
    v1 = cd[idx]
    v2 = cd[idx]
    s = Solver()
    s.add(cd.calldatasize == 4)
    s.add((v1 == v2) == False)  # noqa: E712  (must be unsat)
    assert s.check() != sat


def test_stepped_slice_element_count():
    # step != 1 must yield ceil(span/step) elements, not span elements
    cd = ConcreteCalldata(0, list(range(10)))
    vals = [v.value for v in cd[0:10:2]]
    assert vals == [0, 2, 4, 6, 8]
    vals = [v.value for v in cd[1:8:3]]
    assert vals == [1, 4, 7]


def test_wraparound_slice_rejected():
    # stop < start wraps mod 2^256 -> astronomically large span; must
    # raise instead of hanging
    from mythril_tpu.laser.ethereum.state.calldata import Z3IndexingError

    cd = ConcreteCalldata(0, list(range(4)))
    with pytest.raises(Z3IndexingError):
        cd[3:1]
    with pytest.raises(Z3IndexingError):
        cd[0:4:0]
