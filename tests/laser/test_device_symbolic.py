"""Device symbolic lanes: arena construction, decode, exploration.

Exercises the round-2 centerpiece end to end on the CPU mesh: the
taint shadow follows values through stack/memory/storage, the arena
decodes back to solver terms that pin the observed path, and the wave
explorer covers a gated branch with a witness found by flipping the
journal against the arena constraints.
"""

import numpy as np
import pytest

from mythril_tpu.laser.batch.arena import ArenaView
from mythril_tpu.laser.batch.state import Status, make_batch, make_code_table
from mythril_tpu.laser.batch.symbolic import make_sym_batch, sym_run
from mythril_tpu.support.model import get_model

# gate: SSTORE(0, 1) only when calldata byte 0 == 0x42
GATED = bytes(
    [0x60, 0x00, 0x35,  # PUSH1 0; CALLDATALOAD
     0x60, 0xF8, 0x1C,  # PUSH1 248; SHR
     0x60, 0x42, 0x14,  # PUSH1 0x42; EQ
     0x60, 0x0D, 0x57,  # PUSH1 13; JUMPI
     0x00,               # STOP
     0x5B,               # JUMPDEST
     0x60, 0x01, 0x60, 0x00, 0x55,  # PUSH1 1; PUSH1 0; SSTORE
     0x00]
)


def _run_gated(data: bytes):
    table = make_code_table([GATED])
    base = make_batch(1, calldata=[data], caller=0xD00D, address=0xA11CE)
    out, steps, _active = sym_run(make_sym_batch(base), table, max_steps=64)
    return out, int(steps)


def test_arena_records_symbolic_branch():
    out, _ = _run_gated(b"\x00" * 36)
    view = ArenaView(out)
    # CALLDATALOAD + SHR + EQ at minimum
    assert view.count >= 3
    journal = view.journal(0)
    assert len(journal) == 1
    pc, taken, tid = journal[0]
    assert pc == 11 and taken is False and tid > 0


def test_arena_terms_pin_the_path():
    out, _ = _run_gated(b"\x00" * 36)
    view = ArenaView(out)

    # the untaken path: constraints must be satisfiable with cd0 != 0x42
    stay = view.path_condition(0, 0, flip_last=False)
    model = get_model(tuple(stay), enforce_execution_time=False)
    assert model.eval_int(view.calldata_byte(0)) != 0x42

    # the flipped path: any witness must start with the gate byte
    flipped = view.path_condition(0, 0, flip_last=True)
    model = get_model(tuple(flipped), enforce_execution_time=False)
    assert model.eval_int(view.calldata_byte(0)) == 0x42


def test_taint_flows_through_memory_roundtrip():
    # MSTORE the calldata word, MLOAD it back, branch on it
    code = bytes(
        [0x60, 0x00, 0x35,        # CALLDATALOAD(0)
         0x60, 0x20, 0x52,        # MSTORE(0x20, x)
         0x60, 0x20, 0x51,        # MLOAD(0x20)
         0x60, 0x0E, 0x57,        # JUMPI -> 14
         0x00,
         0x00,
         0x5B, 0x00]
    )
    table = make_code_table([code])
    base = make_batch(1, calldata=[b"\x00" * 4])
    out, _, _ = sym_run(make_sym_batch(base), table, max_steps=32)
    view = ArenaView(out)
    journal = view.journal(0)
    assert len(journal) == 1
    assert journal[0][2] > 0  # condition stayed symbolic through memory


def test_explorer_covers_gate_with_device_witness():
    from mythril_tpu.laser.batch.explore import DeviceSymbolicExplorer

    explorer = DeviceSymbolicExplorer(
        GATED.hex(), calldata_len=36, lanes=4, waves=3, steps_per_wave=64
    )
    outcome = explorer.run()
    stats = outcome["stats"]
    assert stats["device_steps"] > 0
    assert stats["forks_feasible"] >= 1
    assert (11, True) in explorer.covered and (11, False) in explorer.covered
    assert any(d[:1] == b"\x42" for d in explorer.corpus)


def test_prepass_runs_in_analyze_when_forced(monkeypatch):
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.ethereum.evmcontract import EVMContract
    from mythril_tpu.support.support_args import args

    monkeypatch.setattr(args, "device_prepass", "always")
    contract = EVMContract(GATED.hex(), name="GATE")
    sym = SymExecWrapper(
        contract,
        0xA11CE,
        "bfs",
        max_depth=32,
        execution_timeout=30,
        create_timeout=10,
        transaction_count=1,
    )
    assert sym.device_exploration is not None
    assert sym.device_exploration["stats"]["device_steps"] > 0
    assert any(
        "device_symbolic_prepass" in info.as_dict()
        for info in sym.execution_info
    )


# gate: ASSERT_FAIL (0xfe) only when calldata byte 0 == 0x42 — the
# minimal SWC-110 shape the prepass must prove with a banked witness
GATEFAIL = bytes(
    [0x60, 0x00, 0x35,  # PUSH1 0; CALLDATALOAD
     0x60, 0xF8, 0x1C,  # PUSH1 248; SHR
     0x60, 0x42, 0x14,  # PUSH1 0x42; EQ
     0x60, 0x0D, 0x57,  # PUSH1 13; JUMPI
     0x00,               # STOP
     0x5B,               # JUMPDEST (13)
     0xFE]               # ASSERT_FAIL (14)
)


def test_prepass_witness_becomes_issue(monkeypatch):
    """The explorer's trigger bank flows into the analysis as a
    concrete SWC-110 Issue, and fire_lasers dedups it against the host
    walk's own finding (VERDICT r2 task 1)."""
    from mythril_tpu.analysis.security import fire_lasers
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.ethereum.evmcontract import EVMContract
    from mythril_tpu.support.support_args import args

    monkeypatch.setattr(args, "device_prepass", "always")
    contract = EVMContract(GATEFAIL.hex(), name="GATEFAIL")
    sym = SymExecWrapper(
        contract,
        0xA11CE,
        "bfs",
        max_depth=32,
        execution_timeout=60,
        create_timeout=10,
        transaction_count=1,
    )
    assert [(i.address, i.swc_id) for i in sym.device_issues] == [(14, "110")]
    issue = sym.device_issues[0]
    assert issue.provenance == "device-prepass"
    assert issue.title == "Exception State"
    step = issue.transaction_sequence["steps"][0]
    assert step["input"].startswith("0x42")
    assert step["address"] == hex(0xA11CE)
    assert sym.device_exploration["stats"]["witness_issues"] == 1

    merged = fire_lasers(sym)
    hits = [(i.address, i.swc_id) for i in merged]
    assert hits.count((14, "110")) == 1  # found by both engines, reported once


def test_device_coverage_skips_host_feasibility(monkeypatch):
    """Branch directions the device concretely executed skip their
    feasibility query in the host walk (guided sparse pruning)."""
    from mythril_tpu.analysis.symbolic import SymExecWrapper
    from mythril_tpu.ethereum.evmcontract import EVMContract
    from mythril_tpu.support.support_args import args

    monkeypatch.setattr(args, "device_prepass", "always")
    contract = EVMContract(GATEFAIL.hex(), name="GATEFAIL")
    sym = SymExecWrapper(
        contract,
        0xA11CE,
        "bfs",
        max_depth=32,
        execution_timeout=60,
        create_timeout=10,
        transaction_count=1,
    )
    assert sym.laser.device_covered  # prepass seeded the guide
    assert sym.laser.device_precovered_skips >= 1


# 2-transaction pattern: tx1 (cd0==1) stores CALLER as owner; tx2
# (cd0==2) selfdestructs only when SLOAD(0) == CALLER — the
# suicide.sol.o shape the multi-transaction explorer must crack alone
KILL2TX = bytes([
    0x60, 0x00, 0x35, 0x60, 0xF8, 0x1C,              # cd0
    0x80, 0x60, 0x01, 0x14, 0x60, 0x15, 0x57,        # ==1 -> SET
    0x80, 0x60, 0x02, 0x14, 0x60, 0x1B, 0x57,        # ==2 -> KILL
    0x00,
    0x5B, 0x33, 0x60, 0x00, 0x55, 0x00,              # SET: SSTORE(0,CALLER)
    0x5B, 0x60, 0x00, 0x54, 0x33, 0x14,              # KILL: SLOAD(0)==CALLER
    0x60, 0x25, 0x57, 0x00,
    0x5B, 0x33, 0xFF,                                # SELFDESTRUCT(CALLER)
])


def test_multi_tx_device_explorer_finds_storage_gated_selfdestruct():
    """VERDICT r2 task 3: a 2-tx vulnerability found by the device
    explorer alone — the storage journal persists across waves as a
    carry, and the witness records the full transaction prefix."""
    from mythril_tpu.laser.batch.explore import DeviceSymbolicExplorer

    explorer = DeviceSymbolicExplorer(
        KILL2TX.hex(), calldata_len=36, lanes=8, waves=4,
        steps_per_wave=64, transaction_count=2,
    )
    outcome = explorer.run()
    stats = outcome["stats"]
    assert stats["transactions"] == 2
    assert stats["carries_banked"] >= 1  # the device mutation pruner banked tx1
    kills = outcome["triggers"].get("selfdestruct")
    assert kills, "2-tx selfdestruct not found by the device explorer"
    witness = kills[0]
    assert witness["pc"] == 39
    assert bytes.fromhex(witness["input"])[0] == 0x02
    assert len(witness["prefix"]) == 1
    assert bytes.fromhex(witness["prefix"][0])[0] == 0x01


def test_multi_tx_witness_becomes_two_step_swc106_issue():
    """The 2-tx trigger renders as an SWC-106 Issue whose transaction
    sequence replays both steps in order."""
    from mythril_tpu.analysis.prepass import witness_issues
    from mythril_tpu.ethereum.evmcontract import EVMContract
    from mythril_tpu.laser.batch.explore import DeviceSymbolicExplorer

    explorer = DeviceSymbolicExplorer(
        KILL2TX.hex(), calldata_len=36, lanes=8, waves=4,
        steps_per_wave=64, transaction_count=2,
    )
    outcome = explorer.run()
    contract = EVMContract(KILL2TX.hex(), name="KILL2TX")
    issues = witness_issues(contract, outcome, 0xA11CE)
    kills = [i for i in issues if i.swc_id == "106"]
    assert kills and kills[0].provenance == "device-prepass"
    steps = kills[0].transaction_sequence["steps"]
    assert len(steps) == 2
    assert steps[0]["input"].startswith("0x01")
    assert steps[1]["input"].startswith("0x02")
