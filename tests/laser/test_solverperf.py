"""Device-first solver funnel suite (ISSUE 9, `-m solverperf`).

Pins the four contracts of the inverted funnel:

1. **Parity** — the device-first funnel (batched diversified-SLS
   dispatch + enumeration + cube-and-conquer first, host CDCL as the
   escalation ladder) reports the SAME issue-bearing outcomes as the
   legacy host-first order, on the fault suite AND on every module
   positive-fixture contract — zero issue-set regressions is the
   acceptance bar.
2. **Deterministic heterogeneous seeding** — same seed, same verdicts
   and witnesses; the polarity-seeded lane band starts at the
   program's own constants (a wide constant equality solves at step
   0 with seeding on, and doesn't without).
3. **Cube-and-conquer** — cube splits partition the search space
   (roundtrip: an original witness lands in exactly one cube), and a
   complete enumeration over an exhausted cube lattice yields a
   device-OWNED unsat verdict.
4. **Witness validation** — a corrupted device model is rejected
   (WITNESS_INVALID), never surfaced as sat.

The conftest turns `args.device_first` off for the rest of the suite
(per-wave batched dispatches re-compile per shape bucket — too slow
for tier-1 everywhere); this file re-enables it, mirroring the
specialize suite's pattern.
"""

import importlib.util
import time
from pathlib import Path

import pytest

from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer
from mythril_tpu.laser.smt import ULT, symbol_factory
from mythril_tpu.laser.smt.evalterm import eval_term
from mythril_tpu.laser.smt.solver import portfolio
from mythril_tpu.laser.smt.solver.solver import lower
from mythril_tpu.support.support_args import args as support_args

pytestmark = pytest.mark.solverperf

#: the fault-suite shapes (tests/laser/test_pipeline.py)
WRITER = "6001600055600060015500"
BRANCHER = "600035600757005b600160005500"
KILLABLE = "33ff"
GATED = "60003560f81c604214600d57005b600160005500"


@pytest.fixture(autouse=True)
def _device_first():
    """Re-enable the inverted funnel for this suite only."""
    prev = support_args.device_first
    support_args.device_first = True
    yield
    support_args.device_first = prev


def bv(name, width=64):
    return symbol_factory.BitVecSym(name, width)


def val(v, width=64):
    return symbol_factory.BitVecVal(v, width)


def lowered(*constraints):
    out, _ = lower([c.raw for c in constraints])
    return out


def _explore(codes, device_first, **kw):
    kw.setdefault("lanes_per_contract", 8)
    kw.setdefault("waves", 3)
    kw.setdefault("steps_per_wave", 64)
    kw.setdefault("transaction_count", 1)
    support_args.device_first = device_first
    ex = DeviceCorpusExplorer(codes, **kw)
    return ex, ex.run()


def _fingerprint(contract):
    """The issue-bearing outcome of one contract (what issue synthesis
    reads): coverage, trigger pcs per kind, evidence pairs."""
    return (
        tuple(map(tuple, contract["covered_branches"])),
        {
            kind: tuple(sorted(t["pc"] for t in bucket))
            for kind, bucket in contract["triggers"].items()
        },
        tuple(sorted((e["class"], e["pc"]) for e in contract["evidence"])),
    )


# -- 1. the parity differential (acceptance criterion) ----------------------


def test_inverted_funnel_parity_on_fault_suite():
    """Device-first and host-first funnels must report the SAME
    issue-bearing outcomes on the fault suite — including the gated
    shape whose taken direction needs a solver-derived flip witness —
    and the device must actually OWN verdicts in the inverted run.
    Lean portfolio knobs: parity is about the funnel ORDER, and the
    small shapes keep the XLA compile bill inside the tier-1 window
    (the production knob set runs on the bench, not here)."""
    codes = [KILLABLE, WRITER, BRANCHER, GATED]
    with portfolio.portfolio_overrides(cube_depth=0, first_pass_steps=64):
        ex_dev, dev = _explore(
            codes, True, seed=7,
            portfolio_candidates=16, portfolio_steps=64,
        )
        ex_host, host = _explore(
            codes, False, seed=7,
            portfolio_candidates=16, portfolio_steps=64,
        )
    for d, h in zip(dev["contracts"], host["contracts"]):
        assert _fingerprint(d) == _fingerprint(h)
    # the differential is not trivially empty: the gate was flipped
    covered_gate = {
        tuple(b) for b in dev["contracts"][3]["covered_branches"]
    }
    assert (11, True) in covered_gate and (11, False) in covered_gate
    # the inverted funnel's whole point: the accelerator answers first
    assert ex_dev.stats.device_sat + ex_dev.stats.device_unsat >= 1
    assert ex_dev.stats.host_sat <= ex_host.stats.host_sat
    # host-first keeps the legacy ownership (sprint answers first)
    assert ex_host.stats.host_sat >= 1


@pytest.mark.slow
def test_inverted_funnel_parity_on_module_fixtures():
    """Zero issue-set regressions across every module positive-fixture
    contract (all 14 detection modules' minimal trigger shapes): the
    inverted funnel explores them to the same outcomes as host-first.
    Heavy (two corpus explorations) — rides the solverperf/slow tiers.
    """
    spec = importlib.util.spec_from_file_location(
        "module_fixtures",
        Path(__file__).parent.parent
        / "analysis"
        / "test_module_positive_fixtures.py",
    )
    fixtures_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fixtures_mod)
    codes = [code for code, _swc in fixtures_mod.FIXTURES.values()]
    assert len(codes) >= 14
    # parity is about the funnel ORDER, not the knob set: run both
    # orders with a lean portfolio (few candidates, short first pass,
    # no cube fan) so two full corpus explorations fit the tier
    with portfolio.portfolio_overrides(cube_depth=0, first_pass_steps=64):
        _, dev = _explore(
            codes, True, seed=3, waves=2, lanes_per_contract=4,
            portfolio_candidates=16, portfolio_steps=64,
        )
        _, host = _explore(
            codes, False, seed=3, waves=2, lanes_per_contract=4,
            portfolio_candidates=16, portfolio_steps=64,
        )
    for name, d, h in zip(
        fixtures_mod.FIXTURES, dev["contracts"], host["contracts"]
    ):
        assert _fingerprint(d) == _fingerprint(h), name


# -- 2. deterministic heterogeneous seeding ---------------------------------


def test_diversified_search_is_deterministic():
    """Same seed -> same verdicts AND same witnesses, twice: the
    heterogeneous lane strategies (noise sweep, greedy/random split,
    Luby restarts) are all driven by the one PRNG key chain."""
    queries = [
        lowered(bv("dx") + 5 == 12),
        lowered(bv("dy", 32) * 3 == 21, ULT(bv("dy", 32), val(100, 32))),
    ]
    # small shapes: one fresh kernel class is enough to pin the
    # determinism contract (the second call must hit the cache)
    with portfolio.portfolio_overrides(
        cube_depth=0, first_pass_steps=32
    ):
        a = portfolio.device_solve_batch(queries, candidates=8, seed=13)
        b = portfolio.device_solve_batch(queries, candidates=8, seed=13)
    assert [v.status for v in a] == [v.status for v in b]
    assert [v.assignment for v in a] == [v.assignment for v in b]
    for v, q in zip(a, queries):
        if v.status == "sat":
            assert all(eval_term(c, v.assignment) for c in q)


def test_polarity_seeding_starts_at_program_constants():
    """The seeded lane band begins at the program's OWN constants: a
    wide constant disjunction is solved by the INITIAL candidates
    alone (steps=0) with seeding on, and cannot be without it (the
    constants are astronomically unlikely to be drawn at random).
    A plain `var == const` would be bound away by the preprocessor,
    so the magic rides an Or — no binding propagation."""
    from mythril_tpu.laser.smt import Or

    magic_a = 0xDEADBEEFCAFEBABE1234567890ABCDEF
    magic_b = 0x11111111222222223333333344444444
    px = bv("px", 128)
    q = lowered(
        Or(px == val(magic_a, 128), px == val(magic_b, 128))
    )
    prog = portfolio.compile_program(q)
    assert prog is not None and prog.n_consts >= 2
    with portfolio.portfolio_overrides(seeded_frac=0.5):
        asn = portfolio.device_check(q, candidates=8, steps=0, prog=prog)
    assert asn is not None and asn["px"] in (magic_a, magic_b)
    with portfolio.portfolio_overrides(seeded_frac=0.0):
        asn = portfolio.device_check(q, candidates=8, steps=0, prog=prog)
    assert asn is None


# -- 3. cube-and-conquer ----------------------------------------------------


def test_cube_split_merge_roundtrip():
    """The 2^depth cubes PARTITION the original space: every cube
    compiles, pin sets are pairwise distinct, and a witness of the
    original query satisfies exactly ONE cube (the merge direction)."""
    q = lowered(bv("cx") + 1 == bv("cy"))
    prog = portfolio.compile_program(q)
    cubes = portfolio.cube_queries(q, prog, depth=3)
    assert len(cubes) == 8
    for cq in cubes:
        assert portfolio.compile_program(cq) is not None
    witness = {"cx": 41, "cy": 42}
    assert all(eval_term(c, witness) for c in q)
    hits = sum(
        1 for cq in cubes if all(eval_term(c, witness) for c in cq)
    )
    assert hits == 1
    # any cube witness is an original witness (cube = original + pins)
    for cq in cubes:
        assert all(c in cq for c in q)


def test_exhausted_cube_space_is_device_owned_unsat():
    """A complete program over a small variable space enumerates to a
    device-OWNED unsat when every cube chunk of the lattice comes back
    empty — and to a validated sat when a chunk holds a witness."""
    z = bv("uz", 16)
    unsat_q = lowered(ULT(z, val(2, 16)), ULT(val(5, 16), z))
    sat_q = lowered((z & 0xFF) == 0x42)
    verdicts = portfolio.device_solve_batch([unsat_q, sat_q])
    assert verdicts[0].status == "unsat"
    assert verdicts[0].via == "enum"
    assert verdicts[1].status == "sat"
    assert all(eval_term(c, verdicts[1].assignment) for c in sat_q)
    # chunked lattice: force multiple cube chunks and keep the verdict
    with portfolio.portfolio_overrides(enum_chunk_bits=10):
        prog = portfolio.compile_program(unsat_q)
        verdict, asn = portfolio.device_enumerate(prog)
    assert (verdict, asn) == ("unsat", None)


def test_segmented_programs_never_claim_unsat(monkeypatch):
    """Segmentation (dropping constraints outside the device language)
    is SAT-only sound: an incomplete program must never enumerate to
    unsat, however small its kept space is. The SLS stage is stubbed
    empty — the contract under test is the enumeration GATING, and a
    real search would only add a kernel compile."""
    from mythril_tpu.laser.smt import terms

    z = bv("sz", 8)
    # an unsat pair over 8 bits, plus one raw select (outside the
    # device language: injected directly, as the portfolio tests do)
    sel = terms.select(
        terms.array_var("SEG", 256, 256), terms.bv_var("si", 256)
    )
    q = lowered(ULT(z, val(2, 8)), ULT(val(5, 8), z)) + [
        terms.eq(sel, terms.bv_const(5, 256))
    ]
    prog, dropped, loss = portfolio.compile_program_relaxed(q)
    assert prog is not None and dropped == 1 and not prog.complete
    assert portfolio.device_enumerate(prog) == ("unknown", None)
    monkeypatch.setattr(portfolio, "_sls_batch", lambda live, *a, **kw: {})
    verdicts = portfolio.device_solve_batch([q], cube_depth=0)
    assert verdicts[0].status == "unknown"


# -- 4. witness validation --------------------------------------------------


def test_corrupted_device_model_is_rejected(monkeypatch):
    """A corrupted device assignment (transfer fault, decode bug) must
    fail the host-side validation gate and degrade to unknown with
    WITNESS_INVALID — never surface as sat."""
    q = lowered(bv("wx") + 5 == 12)

    def corrupted(live, *a, **kw):
        return {i: {"wx": 9999} for i, _prog in live}

    monkeypatch.setattr(portfolio, "_sls_batch", corrupted)
    verdicts = portfolio.device_solve_batch([q], cube_depth=0)
    assert verdicts[0].status == "unknown"
    assert verdicts[0].loss == "WITNESS_INVALID"


def test_validate_witness_accepts_real_models():
    q = lowered(bv("vx") + 5 == 12)
    prog = portfolio.compile_program(q)
    assert portfolio.validate_witness(prog, {"vx": 7})
    assert not portfolio.validate_witness(prog, {"vx": 8})


# -- escalation ladder ------------------------------------------------------


def test_sprint_cap_is_configurable_and_recorded(tmp_path):
    """The escalation ladder's cap comes from args.sprint_cap_s (env
    MYTHRIL_SPRINT_CAP_S at startup), and a capped query's loss
    artifact records SPRINT_PREEMPTED with the ACTUAL cap."""
    import json
    import os

    from mythril_tpu.observe import querylog

    ex = DeviceCorpusExplorer(
        [KILLABLE], lanes_per_contract=4, waves=1, steps_per_wave=16
    )
    prev = support_args.sprint_cap_s
    querylog.configure_capture(str(tmp_path))
    try:
        support_args.sprint_cap_s = 0.0
        assert ex._sprint_cap_s() == 0.0
        x = bv("capx", 16)
        batch = [[x + 5 == 12]]
        out = [None]
        capped, survivors = ex._sprint_flips(batch, out)
        assert capped == {0} and out == [None]
    finally:
        support_args.sprint_cap_s = prev
        querylog.configure_capture(None)
    artifacts = list(tmp_path.glob("q-*.json"))
    assert len(artifacts) == 1
    doc = json.loads(artifacts[0].read_text())
    obs = doc["observations"][-1]
    assert obs["loss_reason"] == "SPRINT_PREEMPTED"
    assert obs["detail"] == {"sprint_cap_s": 0.0}
    assert doc["origin"] == "flip-frontier"

    # the env seed: a fresh Args() picks MYTHRIL_SPRINT_CAP_S up
    from mythril_tpu.support.support_args import _env_float

    os.environ["MYTHRIL_SPRINT_CAP_S"] = "2.5"
    try:
        assert _env_float("MYTHRIL_SPRINT_CAP_S", 5.0) == 2.5
    finally:
        del os.environ["MYTHRIL_SPRINT_CAP_S"]
    assert _env_float("MYTHRIL_SPRINT_CAP_S", 5.0) == 5.0


def test_race_margin_histogram_records_near_miss(monkeypatch):
    """A race the device wins AFTER the host answered records its
    margin in mtpu_solver_race_margin_seconds (the grace-window tuning
    signal) — and one that finished empty records nothing."""
    from mythril_tpu.laser.smt.solver import device_race as dr
    from mythril_tpu.observe.registry import registry

    def slow_win(lowered, candidates=32, steps=256):
        time.sleep(0.05)
        return {"m": 1}

    monkeypatch.setattr(portfolio, "device_check", slow_win)
    hist = registry().histogram("mtpu_solver_race_margin_seconds").labels()
    before = hist.count
    race = dr.DeviceRace(["t1", "t2"])
    assert race.started
    race.note_host_answered()  # host answers while the race runs
    deadline = time.time() + 5
    while race.poll() is dr.PENDING and time.time() < deadline:
        time.sleep(0.01)
    assert race.poll() == {"m": 1}
    assert hist.count == before + 1
    assert hist.sum >= 0.0

    def empty(lowered, candidates=32, steps=256):
        return None

    monkeypatch.setattr(portfolio, "device_check", empty)
    race2 = dr.DeviceRace(["t"])
    deadline = time.time() + 5
    while race2.poll() is dr.PENDING and time.time() < deadline:
        time.sleep(0.01)
    race2.note_host_answered()
    assert hist.count == before + 1  # empty finish: no near-miss
