"""Round-4 explorer behaviors: phase guarantees, degraded-lane
counters, and the solver race plumbing.

Reference anchors: the multi-transaction driver these phases mirror is
mythril/laser/ethereum/svm.py:189-219; the `--parallel-solving` the
race replaces is mythril/laser/smt/solver/__init__.py:8-9.
"""

import time

import pytest

from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

#: PUSH1 1; PUSH1 0; SSTORE; STOP — mutates storage then halts, so the
#: end state banks a carry and transaction 2 has somewhere to go
MUTATOR = "600160005500"

#: PUSH1 1; PUSH2 0x8000; MSTORE — offset 32KiB clears the gas model
#: (memory expansion ~3k gas) but overflows the explorer's 16KiB
#: device memory capacity, degrading the lane to ERR_MEM
MEM_BUSTER = "600161800052"


def test_later_phases_survive_a_spent_budget():
    """A budget that dies during phase 1 must not cancel phase 2: each
    phase's opening wave is unconditional (bounded overshoot), because
    -t N is the threat model, not an optimization."""
    ex = DeviceCorpusExplorer(
        [MUTATOR],
        lanes_per_contract=8,
        waves=4,
        steps_per_wave=64,
        budget_s=0.0,  # spent before the first budget check
        transaction_count=2,
    )
    out = ex.run()
    assert out["stats"]["transactions"] == 2
    assert out["stats"]["carries_banked"] >= 1


def test_stop_event_cancels_remaining_phases():
    class Stop:
        def is_set(self):
            return True

    ex = DeviceCorpusExplorer(
        [MUTATOR],
        lanes_per_contract=8,
        waves=4,
        steps_per_wave=64,
        budget_s=10.0,
        transaction_count=2,
        stop_event=Stop(),
    )
    out = ex.run()
    assert out["stats"]["transactions"] <= 1
    assert out["stats"]["waves"] == 0


def test_degraded_lane_counters():
    """ERR_MEM lanes are counted: the lean device caps are a measured
    trade-off, not a hope (VERDICT r3 #10)."""
    ex = DeviceCorpusExplorer(
        [MEM_BUSTER],
        lanes_per_contract=8,
        waves=1,
        steps_per_wave=32,
        transaction_count=1,
    )
    out = ex.run()
    assert out["stats"]["lanes_degraded_mem"] >= 1
    assert out["stats"]["lanes_degraded_unsupported"] == 0


def test_device_busy_is_set_during_run(monkeypatch):
    """Explorations own the chip: the busy flag must be up while waves
    run so solver races queue behind them instead of starting."""
    from mythril_tpu.laser.smt.solver.device_race import DEVICE_BUSY

    seen = []
    ex = DeviceCorpusExplorer(
        [MUTATOR], lanes_per_contract=8, waves=1, steps_per_wave=32
    )
    original = ex._dispatch_wave

    def spy(payload):
        seen.append(DEVICE_BUSY.is_set())
        return original(payload)

    monkeypatch.setattr(ex, "_dispatch_wave", spy)
    ex.run()
    assert seen and all(seen)
    assert not DEVICE_BUSY.is_set()


def test_device_race_poll_protocol():
    """poll() walks PENDING -> (assignment | FAILED) exactly once and
    the in-flight slot is always released."""
    from mythril_tpu.laser.smt.solver import device_race as dr

    class SlowPortfolio:
        @staticmethod
        def device_check(lowered, candidates=32, steps=256):
            time.sleep(0.2)
            return {"x": 7}

    import mythril_tpu.laser.smt.solver.portfolio as portfolio

    real = portfolio.device_check
    portfolio.device_check = SlowPortfolio.device_check
    try:
        race = dr.DeviceRace(["fake-term", "fake-term-2"])
        assert race.started
        assert race.poll() is dr.PENDING
        deadline = time.time() + 5
        while race.poll() is dr.PENDING and time.time() < deadline:
            time.sleep(0.01)
        assert race.poll() == {"x": 7}
    finally:
        portfolio.device_check = real
    # slot released: a fresh race can start
    portfolio.device_check = lambda lowered, candidates=32, steps=256: None
    try:
        race2 = dr.DeviceRace(["t"])
        assert race2.started
        deadline = time.time() + 5
        while race2.poll() is dr.PENDING and time.time() < deadline:
            time.sleep(0.01)
        assert race2.poll() is dr.FAILED
    finally:
        portfolio.device_check = real


def test_race_wins_reach_check_terms(monkeypatch):
    """A device-race witness must surface as a sat verdict (with the
    soundness gate applied) when the CDCL marathon is still grinding."""
    from mythril_tpu.laser.smt import symbol_factory
    from mythril_tpu.laser.smt.solver import solver as S
    from mythril_tpu.laser.smt.solver.solver_statistics import (
        SolverStatistics,
    )

    x = symbol_factory.BitVecSym("race_x", 16)
    y = symbol_factory.BitVecSym("race_y", 16)
    # neither constraint pins a variable alone, so lower()'s binding
    # propagation cannot collapse the set below the race threshold
    raw = [(x * y == 35).raw, (x + y == 12).raw]

    # force every CDCL call to come back unknown so only the race can
    # answer (a conflict budget cannot do this: easy queries solve by
    # pure propagation with zero conflicts)
    blaster, session = S._blast_session()
    monkeypatch.setattr(
        type(session),
        "solve",
        lambda self, *a, **k: (S.native_sat.UNKNOWN, None),
    )

    class InstantWin:
        PENDING = "pending"
        FAILED = "failed"

        def __init__(self, lowered, candidates=32, steps=256):
            self.started = True

        def poll(self):
            return {"race_x": 5, "race_y": 7}

    from mythril_tpu.laser.smt.solver import device_race as dr

    monkeypatch.setattr(dr, "DeviceRace", InstantWin)
    monkeypatch.setattr(dr, "race_available", lambda: True)
    monkeypatch.setattr(S, "device_solving_enabled", lambda: True)

    stats = SolverStatistics()
    before = stats.device_sat_count
    status, model = S.check_terms(raw, timeout_ms=4000)
    assert status == S.sat
    assert model.assignment["race_x"] == 5
    assert stats.device_sat_count == before + 1
