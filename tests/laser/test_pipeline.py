"""The pipelined wave engine (ISSUE 4): double-buffered async
dispatch, device-side evidence compaction, donated-arena reseed, the
background checkpoint writer, and the service's two pipeline slots.

The acceptance bar: the pipelined and lock-step (--no-pipeline)
schedules emit identical issue sets on the fault-suite contracts, an
XLA fault surfacing asynchronously on the in-flight wave N+1 is
attributed and retried correctly, and the compacted readback carries
exactly what the full-table harvest carried. Everything runs on CPU
JAX with the tiny hand-assembled fixtures the resilience suite uses.
"""

import numpy as np
import pytest

from mythril_tpu.laser.batch.arena import ArenaView
from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer
from mythril_tpu.laser.batch.state import (
    make_batch,
    make_code_table,
    storage_dict_from,
)
from mythril_tpu.laser.batch.symbolic import make_sym_batch, sym_run
from mythril_tpu.support import resilience

# tests/laser is not a package: pytest's rootdir import mode puts this
# directory on sys.path, so the harness imports flat
from faultinject import device_faults  # noqa: E402

pytestmark = pytest.mark.pipeline

#: PUSH1 1; PUSH1 0; SSTORE; PUSH1 0; PUSH1 1; SSTORE; STOP
WRITER = "6001600055600060015500"
#: CALLDATALOAD(0) branches to a storage write — one symbolic JUMPI
BRANCHER = "600035600757005b600160005500"
#: CALLER; SELFDESTRUCT — banks trigger evidence in one wave
KILLABLE = "33ff"
#: SSTORE(0, 1) only when calldata byte 0 == 0x42 — covering the taken
#: direction needs a solver-derived flip witness
GATED = "60003560f81c604214600d57005b600160005500"


@pytest.fixture(autouse=True)
def _clean_supervisor():
    resilience.disarm_faults()
    resilience.DegradationLog().reset()
    yield
    resilience.disarm_faults()


def _explore(codes, pipeline, **kw):
    kw.setdefault("lanes_per_contract", 8)
    kw.setdefault("waves", 3)
    kw.setdefault("steps_per_wave", 64)
    kw.setdefault("transaction_count", 1)
    ex = DeviceCorpusExplorer(codes, pipeline=pipeline, **kw)
    return ex, ex.run()


def _fingerprint(contract):
    """The issue-bearing outcome of one contract: coverage, trigger
    pcs per kind, evidence (class, pc) pairs — everything issue
    synthesis (analysis/evidence.py) reads."""
    return (
        tuple(map(tuple, contract["covered_branches"])),
        {
            kind: tuple(sorted(t["pc"] for t in bucket))
            for kind, bucket in contract["triggers"].items()
        },
        tuple(sorted((e["class"], e["pc"]) for e in contract["evidence"])),
    )


# -- the differential (acceptance criterion) --------------------------------
def test_differential_issue_sets_match_on_fault_suite():
    """Pipelined and lock-step runs must report the SAME issue set on
    the fault-suite contracts — including the gated shape whose taken
    direction only a flip witness reaches."""
    codes = [KILLABLE, WRITER, BRANCHER, GATED]
    _, piped = _explore(codes, True, seed=7)
    _, lock = _explore(codes, False, seed=7)
    for p, s in zip(piped["contracts"], lock["contracts"]):
        assert _fingerprint(p) == _fingerprint(s)
    # and the differential is not trivially empty
    assert "selfdestruct" in piped["contracts"][0]["triggers"]
    covered_gate = {tuple(b) for b in piped["contracts"][3]["covered_branches"]}
    assert (11, True) in covered_gate and (11, False) in covered_gate


def test_differential_corpora_match_on_branchless_contracts():
    """Branchless contracts exhaust their frontier in the seed wave:
    both schedules bank identical (deterministic-seed) corpora entries
    for them — the corpus divergence budget of the pipeline is the
    extra warm-up stripe only."""
    _, piped = _explore([KILLABLE], True, waves=1, seed=5)
    _, lock = _explore([KILLABLE], False, waves=1, seed=5)
    assert (
        piped["contracts"][0]["corpus_size"]
        == lock["contracts"][0]["corpus_size"]
    )
    assert _fingerprint(piped["contracts"][0]) == _fingerprint(
        lock["contracts"][0]
    )


# -- overlap + accounting ----------------------------------------------------
def test_pipeline_keeps_two_waves_in_flight():
    ex, out = _explore([BRANCHER], True, waves=4)
    s = out["stats"]
    assert s["pipelined"] == 1
    assert s["waves_inflight_max"] == 2
    assert s["waves_overlapped"] >= 1
    assert 0.0 <= s["wave_overlap_ratio"] <= 1.0
    assert 0.0 <= s["device_idle_frac"] <= 1.0


def test_no_pipeline_is_lock_step():
    ex, out = _explore([BRANCHER], False, waves=3)
    s = out["stats"]
    assert s["pipelined"] == 0
    assert s["waves_overlapped"] == 0
    assert s["waves_inflight_max"] <= 1


def test_active_lane_steps_exclude_halted_tail():
    """KILLABLE lanes halt two instructions in while WRITER lanes run
    seven: the wave keeps stepping until the slowest lane halts, and
    the active count must exclude the already-halted stripe (the raw
    product steps x lanes counts it)."""
    _, out = _explore([WRITER, KILLABLE], True, waves=1)
    s = out["stats"]
    assert 0 < s["device_steps"] < s["device_steps_raw"]


# -- device-side evidence compaction ----------------------------------------
def test_compact_readback_equals_full_tables():
    """ArenaView's bucketed transfer must carry exactly what the
    full-table device_get carried: status, halt pc, gas bounds, and
    every storage journal row up to storage_cnt."""
    import jax

    table = make_code_table([bytes.fromhex(WRITER)])
    base = make_batch(4, calldata=[b"\x00" * 4] * 4)
    out, _steps, _active = sym_run(make_sym_batch(base), table, max_steps=64)
    view = ArenaView(out)
    status, pc, keys, vals, cnt = jax.device_get(
        (
            out.base.status,
            out.base.pc,
            out.base.storage_keys,
            out.base.storage_vals,
            out.base.storage_cnt,
        )
    )
    np.testing.assert_array_equal(view.status, status)
    np.testing.assert_array_equal(view.halt_pc, pc)
    for lane in range(4):
        assert storage_dict_from(view.storage_tables(), lane) == (
            storage_dict_from((keys, vals, cnt), lane)
        )
    assert view.bytes_fetched < view.bytes_full


def test_explorer_counts_compacted_evidence_bytes():
    _, out = _explore([WRITER], True, waves=1)
    s = out["stats"]
    assert s["evidence_bytes_per_wave"] > 0
    assert s["evidence_bytes"] < s["evidence_bytes_full"]


# -- donated-arena reseed ----------------------------------------------------
def test_device_reseed_matches_cold_rebuild():
    """From wave 1 on, the explorer reseeds the next wave on device
    out of the previous wave's buffers; the outcome must be identical
    to rebuilding every wave through make_batch."""

    class ColdExplorer(DeviceCorpusExplorer):
        def _dispatch_wave(self, payload):
            self._carcass = None  # force the cold path every wave
            return super()._dispatch_wave(payload)

    kw = dict(
        lanes_per_contract=8,
        waves=4,
        steps_per_wave=64,
        transaction_count=2,
        pipeline=False,
        seed=3,
    )
    warm = DeviceCorpusExplorer([BRANCHER], **kw).run()
    cold = ColdExplorer([BRANCHER], **kw).run()
    assert _fingerprint(warm["contracts"][0]) == _fingerprint(
        cold["contracts"][0]
    )
    assert (
        warm["contracts"][0]["corpus_size"]
        == cold["contracts"][0]["corpus_size"]
    )


# -- async fault containment -------------------------------------------------
def test_async_fault_on_wave_readback_is_attributed_and_retried():
    """A classified fault surfacing at the harvest (the async-dispatch
    readback point) is recorded against the faulted wave and retried
    cold — the exploration completes with full results."""
    with device_faults(times=1):
        _, out = _explore([BRANCHER], True, waves=3)
    counts = resilience.DegradationLog().counts
    assert counts.get("async-device-fault", 0) >= 1
    assert out["stats"]["device_faults"] == 0  # recovered, not abandoned
    covered = {tuple(b) for b in out["contracts"][0]["covered_branches"]}
    assert (5, False) in covered or (5, True) in covered


def test_fault_on_inflight_second_wave_recovers():
    """skip=1 lets wave 0's harvest through and faults the IN-FLIGHT
    wave 1 — the pipeline's retry must rebuild exactly that wave."""
    with device_faults(times=1, skip=1):
        _, out = _explore([BRANCHER], True, waves=3)
    counts = resilience.DegradationLog().counts
    assert counts.get("async-device-fault", 0) >= 1
    assert out["stats"]["device_faults"] == 0
    assert out["stats"]["waves"] >= 2


def test_exhausted_ladder_still_degrades_not_crashes():
    """Past the whole ladder the pipelined run degrades exactly like
    the lock-step one (resilience parity with test_resilience)."""
    with device_faults(times=99):
        ex = DeviceCorpusExplorer(
            [WRITER],
            lanes_per_contract=8,
            waves=2,
            steps_per_wave=64,
            transaction_count=1,
            pipeline=True,
        )
        out = ex.run()
    assert out["stats"]["device_faults"] == 1
    assert not out["contracts"][0]["device_complete"]
    assert resilience.DegradationLog().counts.get("wave-abandoned") == 1


# -- background checkpoint writer -------------------------------------------
def test_checkpoint_writer_flushes_replayable_frontier(tmp_path):
    from mythril_tpu.laser.batch.checkpoint import checkpoint_shape
    from mythril_tpu.laser.batch.explore import replay_wave

    path = str(tmp_path / "wave.npz")
    ex = DeviceCorpusExplorer(
        [BRANCHER],
        lanes_per_contract=8,
        waves=2,
        steps_per_wave=64,
        transaction_count=1,
        checkpoint_path=path,
        pipeline=True,
    )
    out = ex.run()
    # every dispatched wave flushed (pipelining dispatches the warm-up
    # slot too), the writer drained before run() returned, and the
    # LAST flushed frontier replays
    assert out["stats"]["wave_checkpoints"] == out["stats"]["waves"]
    assert checkpoint_shape(path)["lanes"] == 8
    view, _sym, steps = replay_wave(path)
    assert steps > 0
    replayed = set()
    for lane in range(8):
        for pc, taken, _tid in view.journal(lane):
            replayed.add((pc, taken))
    covered = {tuple(b) for b in out["contracts"][0]["covered_branches"]}
    assert replayed <= covered


# -- the service's two pipeline slots ----------------------------------------
def test_service_pipeline_overlaps_waves_from_distinct_jobs():
    from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig
    from mythril_tpu.service.jobs import Job

    engine = AnalysisEngine(
        ServiceConfig(
            stripes=2,
            lanes_per_stripe=4,
            steps_per_wave=64,
            max_waves=3,
            host_walk=False,
            coalesce_wait_s=0.05,
            idle_wait_s=0.02,
            pipeline=True,
        )
    ).start()
    try:
        jobs = [engine.submit(Job(WRITER)), engine.submit(Job(BRANCHER))]
        for job in jobs:
            settled = engine.queue.wait_terminal(job.id, timeout_s=120.0)
            assert settled is not None and settled.state == "done", (
                settled.state if settled else "lost"
            )
        stats = engine.stats()
        pipe = stats["pipeline"]
        assert pipe["enabled"] is True
        assert pipe["overlapped_waves"] >= 1
        assert pipe["wave_overlap_ratio"] > 0
        assert pipe["multi_job_overlaps"] >= 1
        for job in jobs:
            assert job.report["device"]["waves"] >= 1
    finally:
        engine.close()
