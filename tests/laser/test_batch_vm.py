"""Conformance tests for the batched concrete interpreter.

Hand-assembled EVM programs (our analog of the reference's VMTests
harness, reference: tests/laser/evm_testsuite/evm_test.py) run through
the jit'd step kernel; storage/stack/memory/status/gas are compared
against hand-computed EVM semantics.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mythril_tpu.disassembler.asm import assemble, push
from mythril_tpu.laser.batch import (
    Status,
    make_batch,
    make_code_table,
    run,
)
from mythril_tpu.laser.batch.state import mem_bytes, stack_list, storage_dict
from mythril_tpu.support.keccak import keccak256_int

M = 1 << 256


def exec_one(src, calldata=b"", callvalue=0, max_steps=4096):
    code = assemble(src) if not isinstance(src, bytes) else src
    # fixed code_cap so every test reuses one compiled step kernel
    table = make_code_table([code], code_cap=256)
    batch = make_batch(1, calldata=[calldata], callvalue=callvalue)
    out, steps = run(batch, table, max_steps=max_steps)
    return out


def sstore(slot, valsrc):
    """Assemble: SSTORE(slot) = result of valsrc (list of lines)."""
    return valsrc + [push(slot), "SSTORE"]


def test_arithmetic_program():
    src = (
        sstore(0, [push(3), push(4), "ADD"])          # 4+3 = 7
        + sstore(1, [push(3), push(10), "SUB"])        # 10-3 = 7
        + sstore(2, [push(6), push(7), "MUL"])         # 42
        + sstore(3, [push(3), push(100), "DIV"])       # 33
        + sstore(4, [push(7), push(100), "MOD"])       # 2
        + sstore(5, [push(10), push(2), "EXP"])        # 1024
        + sstore(6, [push(5), push(3), push(4), "ADDMOD"])  # (3+4)%5 = 2
        + sstore(7, [push(5), push(3), push(4), "MULMOD"])  # 12%5 = 2
        + ["STOP"]
    )
    out = exec_one(src)
    assert int(out.status[0]) == Status.STOPPED
    assert storage_dict(out, 0) == {0: 7, 1: 7, 2: 42, 3: 33, 4: 2, 5: 1024,
                                    6: 2, 7: 2}


def test_stack_ops_dup_swap():
    # stack: [1, 2, 3]; SWAP2 -> [3, 2, 1]; DUP3 -> [3, 2, 1, 3]
    src = [push(1), push(2), push(3), "SWAP2", "DUP3", "STOP"]
    out = exec_one(src)
    assert stack_list(out, 0) == [3, 2, 1, 3]


def test_comparisons_and_bitwise():
    src = (
        sstore(0, [push(2), push(1), "LT"])  # 1 < 2 -> 1
        + sstore(1, [push(1), push(2), "LT"])  # 2 < 1 -> 0
        + sstore(2, [push(0xF0), push(0x0F), "OR"])
        + sstore(3, [push(1), "NOT"])  # 2^256 - 2
        + sstore(4, [push(0), "ISZERO"])
        + sstore(5, [push(2), push(1), "SHL"])  # 1 << 2 = 4
        + ["STOP"]
    )
    out = exec_one(src)
    got = storage_dict(out, 0)
    assert got[0] == 1 and 1 not in got  # slot1 = 0 filtered as zero
    assert got[2] == 0xFF
    assert got[3] == M - 2
    assert got[4] == 1
    assert got[5] == 4


def test_memory_roundtrip_and_msize():
    src = (
        [push(0xDEADBEEF), push(0x20), "MSTORE"]  # mem[0x20:0x40] = ..beef
        + sstore(0, [push(0x20), "MLOAD"])
        + sstore(1, ["MSIZE"])
        + [push(0xAB), push(0x5F), "MSTORE8"]      # single byte at 0x5f
        + sstore(2, [push(0x40), "MLOAD"])
        + ["STOP"]
    )
    out = exec_one(src)
    got = storage_dict(out, 0)
    assert got[0] == 0xDEADBEEF
    assert got[1] == 0x40
    assert got[2] == 0xAB  # byte at offset 0x5f is the LSB of word at 0x40


def test_jump_loop_sum():
    # sum = 0; i = 10; while i: sum += i; i -= 1;  sstore(0, sum)
    src = [
        push(0),            # sum
        push(10),           # i  -> stack [sum, i]
        "JUMPDEST",         # addr 4: loop head
        "DUP1",
        "ISZERO",
        push(0x15),         # exit
        "JUMPI",
        "DUP1",             # [sum, i, i]
        "SWAP2",            # [i, i, sum]
        "ADD",              # [i, sum+i]
        "SWAP1",            # [sum+i, i]
        push(1),
        "SWAP1",
        "SUB",              # i-1
        push(0x04),
        "JUMP",
        "JUMPDEST",         # addr 0x15: exit
        "POP",
        push(0),
        "SSTORE",
        "STOP",
    ]
    code = assemble(src)
    # verify hand-computed jump targets hold
    assert code[4] == 0x5B and code[0x15] == 0x5B
    out = exec_one(src)
    assert int(out.status[0]) == Status.STOPPED
    assert storage_dict(out, 0) == {0: 55}


def test_calldata_ops():
    cd = bytes.fromhex("a9059cbb") + (0x1234).to_bytes(32, "big")
    src = (
        sstore(0, [push(0), "CALLDATALOAD", push(0xE0), "SHR"])  # selector
        + sstore(1, [push(4), "CALLDATALOAD"])                    # arg
        + sstore(2, ["CALLDATASIZE"])
        # CALLDATACOPY(mem 0, src 4, len 32) then MLOAD(0)
        + sstore(3, [push(32), push(4), push(0), "CALLDATACOPY",
                     push(0), "MLOAD"])
        + ["STOP"]
    )
    out = exec_one(src, calldata=cd)
    got = storage_dict(out, 0)
    assert got[0] == 0xA9059CBB
    assert got[1] == 0x1234
    assert got[2] == 36
    assert got[3] == 0x1234


def test_sha3():
    # keccak256 of 64 zero bytes (fresh memory)
    src = sstore(0, [push(64), push(0), "SHA3"]) + ["STOP"]
    out = exec_one(src)
    assert storage_dict(out, 0)[0] == keccak256_int(bytes(64))


def test_sha3_nonzero_input():
    src = (
        [push(0x0102030405060708), push(0x20), "MSTORE"]
        + sstore(0, [push(0x40), push(0), "SHA3"])
        + ["STOP"]
    )
    out = exec_one(src)
    # mem[0x20:0x40] holds the 32-byte BE word -> hash input is 56 zero
    # bytes followed by the 8 value bytes
    expect = keccak256_int(bytes(56) + (0x0102030405060708).to_bytes(8, "big"))
    assert storage_dict(out, 0)[0] == expect


def test_return_data():
    src = [
        push(0xCAFE), push(0), "MSTORE",
        push(32), push(0), "RETURN",
    ]
    out = exec_one(src)
    assert int(out.status[0]) == Status.RETURNED
    assert int(out.ret_offset[0]) == 0 and int(out.ret_len[0]) == 32
    assert mem_bytes(out, 0, 0, 32) == (0xCAFE).to_bytes(32, "big")


def test_revert_status():
    out = exec_one([push(0), push(0), "REVERT"])
    assert int(out.status[0]) == Status.REVERTED


def test_error_paths():
    # invalid jump destination (into push data)
    out = exec_one([push(1), "JUMP", "STOP"])
    assert int(out.status[0]) == Status.ERR_JUMP
    # stack underflow
    out = exec_one(["ADD", "STOP"])
    assert int(out.status[0]) == Status.ERR_STACK
    # designated invalid opcode
    out = exec_one(bytes([0xFE]))
    assert int(out.status[0]) == Status.INVALID
    # unknown opcode byte
    out = exec_one(bytes([0x21]))
    assert int(out.status[0]) == Status.INVALID
    # unsupported on device -> host takes over
    out = exec_one([push(0)] * 3 + ["CREATE"])
    assert int(out.status[0]) == Status.UNSUPPORTED
    # a CALL to a codeless address executes on device as a transfer
    # (empty-world semantics); STOP after it proves the lane continued
    out = exec_one([push(0)] * 7 + ["CALL", "STOP"])
    assert int(out.status[0]) == Status.STOPPED
    # ... but a self-call needs real code execution -> host takeover
    out = exec_one(
        [push(0)] * 5 + ["ADDRESS"] + [push(0)] + ["CALL"])
    assert int(out.status[0]) == Status.UNSUPPORTED
    # running off the end of code halts like STOP
    out = exec_one([push(1), "POP"])
    assert int(out.status[0]) == Status.STOPPED


def test_env_opcodes():
    src = (
        sstore(0, ["CALLVALUE"])
        + sstore(1, ["CALLER"])
        + sstore(2, ["ADDRESS"])
        + sstore(3, ["TIMESTAMP"])
        + sstore(4, ["NUMBER"])
        + sstore(5, ["CHAINID"])
        + sstore(6, ["CODESIZE"])
        + ["STOP"]
    )
    out = exec_one(src, callvalue=123)
    got = storage_dict(out, 0)
    assert got[0] == 123
    assert got[1] == 0xDEADBEEFDEADBEEF
    assert got[2] == 0xAFFEAFFE
    assert got[3] == 1_600_000_000
    assert got[4] == 10_000_000
    assert got[5] == 1
    assert got[6] == len(assemble(src))


def test_signed_ops_in_program():
    minus2 = M - 2
    src = (
        sstore(0, [push(minus2), push(7), "SDIV"])  # 7 / -2 = -3
        + sstore(1, [push(3), push(minus2), "SMOD"])  # -2 % 3 = -2
        + sstore(2, [push(minus2), push(1), "SLT"])   # 1 < -2 ? 0
        + sstore(3, [push(1), push(minus2), "SLT"])   # -2 < 1 ? 1
        + ["STOP"]
    )
    out = exec_one(src)
    got = storage_dict(out, 0)
    assert got.get(0, 0) == M - 3
    assert got.get(1, 0) == M - 2
    assert 2 not in got
    assert got.get(3, 0) == 1


def test_gas_accounting_simple():
    # PUSH(3) + PUSH(3) + ADD(3) + PUSH(3) + SSTORE(5000..25000) + STOP(0)
    src = [push(1), push(2), "ADD", push(0), "SSTORE", "STOP"]
    out = exec_one(src)
    assert int(out.gas_min[0]) == 3 + 3 + 3 + 3 + 5000
    assert int(out.gas_max[0]) == 3 + 3 + 3 + 3 + 25000


def test_sstore_overwrite_and_sload():
    src = (
        [push(7), push(5), "SSTORE"]
        + [push(9), push(5), "SSTORE"]   # overwrite slot 5
        + sstore(1, [push(5), "SLOAD"])
        + sstore(2, [push(99), "SLOAD"])  # never written -> 0
        + ["STOP"]
    )
    out = exec_one(src)
    got = storage_dict(out, 0)
    assert got[5] == 9 and got[1] == 9 and 2 not in got


def test_heterogeneous_batch():
    """Different contracts + calldata per lane in one batch."""
    prog_a = assemble(sstore(0, [push(2), push(5), "ADD"]) + ["STOP"])
    prog_b = assemble(sstore(0, [push(0), "CALLDATALOAD"]) + ["STOP"])
    prog_c = assemble([push(0), "JUMP"])  # invalid jump
    table = make_code_table([prog_a, prog_b, prog_c], code_cap=256)
    batch = make_batch(
        6,
        code_ids=[0, 1, 2, 0, 1, 2],
        calldata=[b"", (11).to_bytes(32, "big"), b"", b"",
                  (22).to_bytes(32, "big"), b""],
    )
    out, steps = run(batch, table)
    assert storage_dict(out, 0) == {0: 7}
    assert storage_dict(out, 1) == {0: 11}
    assert int(out.status[2]) == Status.ERR_JUMP
    assert storage_dict(out, 3) == {0: 7}
    assert storage_dict(out, 4) == {0: 22}
    assert int(out.status[5]) == Status.ERR_JUMP
    assert [int(s) for s in out.status[:2]] == [Status.STOPPED, Status.STOPPED]


def test_pc_opcode():
    src = [push(0), "POP", "PC"]  # PC at address 3 pushes 3
    out = exec_one(src)
    assert stack_list(out, 0) == [3]


def test_int32_wrap_offset_is_oog():
    """An MSTORE at an offset just below 2**31 must out-of-gas, not wrap
    the int32 end-of-access computation and silently no-op."""
    import jax.numpy as jnp

    from mythril_tpu.laser.batch.run import run
    from mythril_tpu.laser.batch.state import Status, make_batch, make_code_table

    code = (
        bytes.fromhex("6001")                      # PUSH1 1
        + bytes([0x63, 0x7F, 0xFF, 0xFF, 0xE1])    # PUSH4 0x7FFFFFE1
        + bytes.fromhex("5200")                    # MSTORE; STOP
    )
    table = make_code_table([code])
    batch = make_batch(1)._replace(
        gas_budget=jnp.asarray([1000], dtype=jnp.uint32)
    )
    out, _ = run(batch, table, max_steps=16)
    assert int(out.status[0]) == Status.ERR_OOG


def test_extcodesize_and_returndatacopy_device_semantics():
    """EXTCODESIZE answers on device (own size / 0 in an empty world);
    RETURNDATACOPY's zero-length Solidity form is a no-op; everything
    else hands off to the host."""
    import numpy as np

    from mythril_tpu.laser.batch.run import run
    from mythril_tpu.laser.batch.state import (
        Status,
        make_batch,
        make_code_table,
        storage_dict,
    )

    code = bytes([
        0x30, 0x3B, 0x60, 0x00, 0x55,              # EXTCODESIZE(self) -> s0
        0x61, 0xBE, 0xEF, 0x3B, 0x60, 0x01, 0x55,  # EXTCODESIZE(0xbeef) -> s1
        0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x3E,  # RETURNDATACOPY(0,0,0)
        0x00,
    ])
    table = make_code_table([code])
    batch = make_batch(
        2, calldata=[b"", b""], empty_world=np.array([1, 0], np.uint8)
    )
    out, _ = run(batch, table, max_steps=32)
    assert int(out.status[0]) == Status.STOPPED
    assert storage_dict(out, 0) == {0: len(code)}  # foreign size 0 filtered
    # a world that may hold foreign code defers the foreign query
    assert int(out.status[1]) == Status.UNSUPPORTED

    # nonzero-length RETURNDATACOPY is an EVM exception -> host decides
    code2 = bytes([0x60, 0x01, 0x60, 0x00, 0x60, 0x00, 0x3E, 0x00])
    out2, _ = run(make_batch(1, calldata=[b""]), make_code_table([code2]),
                  max_steps=8)
    assert int(out2.status[0]) == Status.UNSUPPORTED
