"""Keccak function-manager constraint tests (reference test strategy:
tests/laser/keccak_tests.py — sat/unsat assertions over the UF model)."""

import pytest

from mythril_tpu.exceptions import UnsatError
from mythril_tpu.laser.ethereum.keccak_function_manager import (
    KeccakFunctionManager,
)
from mythril_tpu.laser.smt import And, Not, symbol_factory
from mythril_tpu.support.model import get_model


@pytest.fixture()
def km():
    return KeccakFunctionManager()


def test_concrete_keccak_is_real_hash(km):
    from mythril_tpu.support.keccak import keccak256

    data = symbol_factory.BitVecVal(42, 256)
    result, cond = km.create_keccak(data)
    expected = int.from_bytes(keccak256((42).to_bytes(32, "big")), "big")
    assert result.value == expected
    # the linking condition itself must be satisfiable
    get_model((cond,))


def test_symbolic_keccak_is_satisfiable(km):
    x = symbol_factory.BitVecSym("kx", 256)
    hash_x, cond = km.create_keccak(x)
    model = get_model((cond,))
    assert model is not None


def test_injectivity_unsat(km):
    """func(x) == func(y) with x != y must be unsat (inverse constraint
    enforces injectivity)."""
    x = symbol_factory.BitVecSym("ix", 256)
    y = symbol_factory.BitVecSym("iy", 256)
    hash_x, cond_x = km.create_keccak(x)
    hash_y, cond_y = km.create_keccak(y)
    with pytest.raises(UnsatError):
        get_model(
            (cond_x, cond_y, hash_x == hash_y, Not(x == y)),
            solver_timeout=20000,
            enforce_execution_time=False,
        )


def test_equal_inputs_give_equal_hashes(km):
    x = symbol_factory.BitVecSym("ex", 256)
    y = symbol_factory.BitVecSym("ey", 256)
    hash_x, cond_x = km.create_keccak(x)
    hash_y, cond_y = km.create_keccak(y)
    model = get_model(
        (cond_x, cond_y, x == y, hash_x == hash_y),
        solver_timeout=20000,
        enforce_execution_time=False,
    )
    assert model is not None


def test_symbolic_can_match_concrete(km):
    """A symbolic input can hash to a concrete input's real hash when
    they are equal (the Or-linkage case)."""
    concrete = symbol_factory.BitVecVal(7, 256)
    concrete_hash, cond_c = km.create_keccak(concrete)
    x = symbol_factory.BitVecSym("mx", 256)
    hash_x, cond_x = km.create_keccak(x)
    model = get_model(
        (cond_c, cond_x, x == concrete, hash_x == concrete_hash),
        solver_timeout=20000,
        enforce_execution_time=False,
    )
    assert model is not None
