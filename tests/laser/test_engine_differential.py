"""Engine-vs-engine differential testing.

Random straight-line bytecode runs through BOTH execution engines —
the batched XLA interpreter and the object-model LASER engine — with
identical concrete inputs; final storage must agree. This catches
divergence bugs in either engine that fixed test vectors miss (the
reference has no second engine to differentiate against). All programs
run as lanes of ONE batch (the batch engine's own idiom), so the whole
sweep costs one compile + one device pass.
"""

from __future__ import annotations

import random
from datetime import datetime

import numpy as np
import pytest

from mythril_tpu.disassembler.disassembly import Disassembly
from mythril_tpu.laser.batch.run import run as batch_run
from mythril_tpu.laser.batch.state import make_batch, make_code_table
from mythril_tpu.laser.ethereum.state.account import Account
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.ethereum.svm import LaserEVM
from mythril_tpu.laser.ethereum.time_handler import time_handler
from mythril_tpu.laser.ethereum.transaction.concolic import execute_message_call
from mythril_tpu.laser.smt import symbol_factory
from mythril_tpu.ops import u256

CALLER = 0xDEADBEEFDEADBEEF
ADDRESS = 0x1234
N_TRIALS = 48

ARITH = [0x01, 0x02, 0x03, 0x04, 0x05, 0x06, 0x07, 0x0A, 0x0B, 0x10,
         0x11, 0x12, 0x13, 0x14, 0x16, 0x17, 0x18, 0x1A, 0x1B, 0x1C, 0x1D]
TERNARY = [0x08, 0x09]  # addmod, mulmod
UNARY = [0x15, 0x19]  # iszero, not


def random_program(rng: random.Random, n_ops: int = 24) -> bytes:
    """Straight-line program with an exact stack-depth model, draining
    the stack into storage slots at the end."""
    code = bytearray()
    depth = 0
    for _ in range(n_ops):
        choice = rng.random()
        if depth >= 2 and choice < 0.45:
            code.append(rng.choice(ARITH))
            depth -= 1
        elif depth >= 3 and choice < 0.55:
            code.append(rng.choice(TERNARY))
            depth -= 2
        elif depth >= 1 and choice < 0.65:
            code.append(rng.choice(UNARY))
        elif depth >= 1 and choice < 0.72 and depth < 14:
            code.append(0x80 + rng.randrange(min(depth, 4)))  # DUPn
            depth += 1
        else:
            n = rng.randrange(1, 5)
            code.append(0x60 + n - 1)  # PUSHn
            code += rng.randbytes(n)
            depth += 1
    slot = 0
    while depth > 0:
        code += bytes([0x60, slot, 0x55])  # PUSH1 slot; SSTORE
        depth -= 1
        slot += 1
    code.append(0x00)  # STOP
    return bytes(code)


def random_memory_program(rng: random.Random, n_ops: int = 10) -> bytes:
    """Memory + SHA3 template: random MSTOREs at word offsets, then
    keccak a window and store the digest — cross-checks the two
    engines' memory models and keccak implementations."""
    code = bytearray()
    for _ in range(n_ops):
        value = rng.randbytes(rng.randrange(1, 33))
        offset = rng.randrange(0, 8) * 32
        code.append(0x60 + len(value) - 1)  # PUSHn value
        code += value
        code += bytes([0x60, offset, 0x52])  # PUSH1 offset; MSTORE
    start = rng.randrange(0, 4) * 32
    length = rng.choice([32, 64, 96])
    code += bytes([0x60, length, 0x60, start, 0x20])  # SHA3(start, len)
    code += bytes([0x60, 0x00, 0x55])  # SSTORE slot 0
    # also store one MLOAD-ed word for the memory readback path
    code += bytes([0x60, start, 0x51, 0x60, 0x01, 0x55])  # MLOAD; SSTORE 1
    code.append(0x00)
    return bytes(code)


def random_branch_program(rng: random.Random) -> bytes:
    """Conditional-branch template: compare two random constants,
    JUMPI to one of two SSTORE arms — cross-checks jump resolution and
    branch semantics concretely."""
    a = rng.randrange(0, 256)
    b = rng.randrange(0, 256)
    cmp_op = rng.choice([0x10, 0x11, 0x14])  # LT GT EQ
    # layout: PUSH1 a PUSH1 b CMP PUSH1 <dest> JUMPI
    #         PUSH1 0xAA PUSH1 0 SSTORE STOP
    # dest:   JUMPDEST PUSH1 0xBB PUSH1 0 SSTORE STOP
    prefix = bytes([0x60, a, 0x60, b, cmp_op])
    fallthrough = bytes([0x60, 0xAA, 0x60, 0x00, 0x55, 0x00])
    dest = len(prefix) + 3 + len(fallthrough)
    code = prefix + bytes([0x60, dest, 0x57]) + fallthrough
    code += bytes([0x5B, 0x60, 0xBB, 0x60, 0x00, 0x55, 0x00])
    return bytes(code)


def run_laser(code: bytes) -> dict:
    world_state = WorldState()
    account = Account(ADDRESS, concrete_storage=True)
    account.code = Disassembly(code.hex())
    world_state.put_account(account)
    account.set_balance(10**18)

    time_handler.start_execution(10000)
    laser = LaserEVM()
    laser.open_states = [world_state]
    laser.time = datetime.now()
    execute_message_call(
        laser,
        callee_address=symbol_factory.BitVecVal(ADDRESS, 256),
        caller_address=symbol_factory.BitVecVal(CALLER, 256),
        origin_address=symbol_factory.BitVecVal(CALLER, 256),
        code=code.hex(),
        gas_limit=8_000_000,
        data=b"",
        gas_price=10,
        value=0,
        track_gas=True,
    )
    assert len(laser.open_states) == 1, "laser run did not finish cleanly"
    storage = {}
    account = laser.open_states[0][symbol_factory.BitVecVal(ADDRESS, 256)]
    for key, value in account.storage.printable_storage.items():
        storage[key.value] = value.value
    return storage


@pytest.fixture(scope="module")
def programs():
    out = []
    for trial in range(N_TRIALS):
        rng = random.Random(90210 + trial)
        if trial % 3 == 0:
            out.append(random_program(rng))
        elif trial % 3 == 1:
            out.append(random_memory_program(rng))
        else:
            out.append(random_branch_program(rng))
    return out


@pytest.fixture(scope="module")
def batch_storages(programs):
    """All programs as lanes of one batch: one compile, one pass."""
    table = make_code_table(programs)
    batch = make_batch(
        len(programs),
        code_ids=list(range(len(programs))),
        caller=CALLER,
        address=ADDRESS,
    )
    out, _steps = batch_run(batch, table, max_steps=512)
    storages = []
    status = np.asarray(out.status)
    keys = np.asarray(out.storage_keys)
    vals = np.asarray(out.storage_vals)
    cnts = np.asarray(out.storage_cnt)
    for lane in range(len(programs)):
        assert int(status[lane]) != 0, f"lane {lane} still live"
        storage = {}
        for k in range(int(cnts[lane])):
            storage[u256.to_int(keys[lane, k])] = u256.to_int(vals[lane, k])
        storages.append(storage)
    return storages


@pytest.mark.parametrize("trial", range(N_TRIALS))
def test_random_programs_agree(trial, programs, batch_storages):
    laser_storage = run_laser(programs[trial])
    laser_nz = {k: v for k, v in laser_storage.items() if v}
    batch_nz = {k: v for k, v in batch_storages[trial].items() if v}
    assert laser_nz == batch_nz, (
        f"divergence on program {programs[trial].hex()}:\n"
        f"laser: { {hex(k): hex(v) for k, v in laser_nz.items()} }\n"
        f"batch: { {hex(k): hex(v) for k, v in batch_nz.items()} }"
    )
