"""`myth solverlab` replay-lab suite (analysis/solverlab.py; tier-1
`solverlab` marker).

The acceptance bar (ISSUE 8): a corpus captured from the fault-suite
contracts replays offline with 100% host-engine agreement against the
live verdicts, the capture->replay pipeline is deterministic (same
verdicts, same content addresses across captures), sharding partitions
the corpus exactly, filters select by loss reason / origin, and the
CLI surface parses.
"""

import json

import pytest

from mythril_tpu import observe
from mythril_tpu.analysis import solverlab
from mythril_tpu.observe import querylog

pytestmark = pytest.mark.solverlab

#: the pipeline suite's fault-suite fixtures (same shapes, same seeds)
#: — GATED's taken direction needs a solver-derived flip witness, so
#: capturing its exploration yields real flip-frontier queries
GATED = "60003560f81c604214600d57005b600160005500"
BRANCHER = "600035600757005b600160005500"


@pytest.fixture(autouse=True)
def _no_capture_leak():
    querylog.configure_capture(None)
    yield
    querylog.configure_capture(None)


def _capture_fault_suite(out_dir) -> list:
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer
    from mythril_tpu.support.model import clear_cache

    # the get_model memo would swallow repeat queries before they
    # reach check_terms (and so the capture hook); every capture run
    # starts from a cold memo, exactly like a fresh process
    clear_cache()
    querylog.configure_capture(str(out_dir))
    try:
        ex = DeviceCorpusExplorer(
            [GATED, BRANCHER],
            lanes_per_contract=8,
            waves=3,
            steps_per_wave=64,
            transaction_count=1,
            seed=7,
        )
        ex.run()
    finally:
        querylog.configure_capture(None)
    return querylog.load_corpus(str(out_dir))


def test_fault_suite_replay_agrees_100_percent(tmp_path):
    corpus = _capture_fault_suite(tmp_path / "corpus")
    assert corpus, "the fault-suite exploration captured no queries"
    assert any(a["origin"] == "flip-frontier" for a in corpus)
    report = solverlab.run(str(tmp_path / "corpus"), engines=["host"])
    host = report["replay"]["host"]
    assert host["agreement"]["disagree"] == 0, report["disagreements"]
    assert host["agreement_pct"] == 100.0
    # host-won queries all carry a loss reason; the waterfall shows it
    assert report["loss_waterfall_sat"]
    assert sum(report["loss_waterfall_sat"].values()) == (
        report["live_verdicts"].get("sat", 0)
    )


def test_capture_replay_determinism(tmp_path):
    """Same exploration captured twice -> identical content addresses;
    same corpus replayed twice -> identical verdict tables."""
    first = _capture_fault_suite(tmp_path / "one")
    second = _capture_fault_suite(tmp_path / "two")
    assert {a["sha"] for a in first} == {a["sha"] for a in second}
    r1 = solverlab.replay_corpus(first, engines=["host"])
    r2 = solverlab.replay_corpus(first, engines=["host"])
    assert r1["replay"]["host"]["verdicts"] == r2["replay"]["host"]["verdicts"]
    assert r1["replay"]["host"]["agreement"] == r2["replay"]["host"]["agreement"]


def test_device_engine_replays_the_corpus(tmp_path):
    """The portfolio engine re-solves the captured flip queries on
    (CPU) device: any witness it finds passes the concrete soundness
    gate, and a miss counts as incomplete, never disagreement."""
    corpus = _capture_fault_suite(tmp_path / "corpus")
    report = solverlab.replay_corpus(
        corpus, engines=["device"], candidates=16, steps=64
    )
    device = report["replay"]["device"]
    assert device["agreement"]["disagree"] == 0, report["disagreements"]
    assert sum(device["verdicts"].values()) == len(corpus)


def test_shard_partitions_exactly(tmp_path):
    corpus = [
        {"sha": f"{i:064x}", "verdict": "sat", "origin": "module",
         "program": {"nodes": [], "roots": []}}
        for i in range(17)
    ]
    shards = [
        solverlab.shard_corpus(corpus, solverlab.parse_shard(f"{i}/4"))
        for i in range(4)
    ]
    seen = [a["sha"] for shard in shards for a in shard]
    assert sorted(seen) == sorted(a["sha"] for a in corpus)
    assert solverlab.parse_shard(None) is None
    with pytest.raises(ValueError):
        solverlab.parse_shard("4/4")
    with pytest.raises(ValueError):
        solverlab.parse_shard("nope")


def test_filters_select_by_reason_and_origin(tmp_path):
    corpus_dir = tmp_path / "corpus"
    _capture_fault_suite(corpus_dir)
    everything = querylog.load_corpus(str(corpus_dir))
    reasons = {a["loss_reason"] for a in everything if a["loss_reason"]}
    assert reasons  # host-won queries carry reasons
    reason = sorted(reasons)[0]
    filtered = querylog.load_corpus(str(corpus_dir), reason=reason)
    assert filtered and all(
        a["loss_reason"] == reason for a in filtered
    )
    flips = querylog.load_corpus(str(corpus_dir), origin="flip-frontier")
    assert all(a["origin"] == "flip-frontier" for a in flips)
    none = querylog.load_corpus(str(corpus_dir), origin="no-such-origin")
    assert none == []


def test_report_mode_skips_solving(tmp_path):
    corpus_dir = tmp_path / "corpus"
    _capture_fault_suite(corpus_dir)
    report = solverlab.run(str(corpus_dir), mode="report")
    assert report["mode"] == "report"
    assert "replay" not in report
    assert report["queries"] >= 1
    assert set(report) >= {
        "live_verdicts", "origins", "buckets",
        "loss_waterfall", "loss_waterfall_sat",
    }
    # the text renderer never chokes on a report-mode dict
    text = solverlab.render_text(report)
    assert "loss waterfall" in text


def test_replay_does_not_mutate_the_corpus(tmp_path):
    corpus_dir = tmp_path / "corpus"
    _capture_fault_suite(corpus_dir)
    before = {
        a["sha"]: len(a["observations"])
        for a in querylog.load_corpus(str(corpus_dir))
    }
    solverlab.run(str(corpus_dir), engines=["host"])
    after = {
        a["sha"]: len(a["observations"])
        for a in querylog.load_corpus(str(corpus_dir))
    }
    assert before == after


def test_cli_surface_parses():
    from mythril_tpu.interfaces.cli import build_parser

    parser = build_parser()
    args = parser.parse_args(
        [
            "solverlab", "replay", "--corpus", "/tmp/x",
            "--engines", "host,device", "--filter", "reason=GATE_DISABLED",
            "--shard", "0/2", "--timeout-ms", "5000", "--json", "--strict",
        ]
    )
    assert args.command == "solverlab"
    assert args.mode == "replay"
    assert args.shard == "0/2"
    args = parser.parse_args(["solverlab", "report", "--corpus", "/tmp/x"])
    assert args.mode == "report"
    # the analyze surface grew the capture flag
    args = parser.parse_args(
        ["analyze", "-c", "33ff", "--capture-queries", "/tmp/q"]
    )
    assert args.capture_queries == "/tmp/q"


def test_run_report_is_json_serializable(tmp_path):
    corpus_dir = tmp_path / "corpus"
    _capture_fault_suite(corpus_dir)
    report = solverlab.run(str(corpus_dir), engines=["host"])
    blob = json.dumps(report, sort_keys=True)
    assert json.loads(blob)["replay"]["host"]["agreement_pct"] == 100.0
