"""Multi-block SHA3 in the step kernel vs the pure-python oracle.

The device absorbs up to SHA_MAX_BLOCKS rate blocks per SHA3 (state.py),
covering every size class the padding rules distinguish: empty input,
intra-block, exactly rate-1 (the 0x81 shared pad byte), exact rate,
rate+1, and multi-block.
"""

import numpy as np
import pytest

from mythril_tpu.laser.batch.run import run
from mythril_tpu.laser.batch.state import (
    Status,
    make_batch,
    make_code_table,
    storage_dict,
)
from mythril_tpu.support.keccak import keccak256

SIZES = [0, 1, 32, 64, 135, 136, 137, 272, 500, 1000]


def _sha_program(length: int) -> bytes:
    """CALLDATACOPY(0,0,L); SSTORE(0, SHA3(0,L)); STOP"""

    def push(v):
        return bytes([0x60, v]) if v < 256 else bytes([0x61, v >> 8, v & 0xFF])

    return (
        push(length) + push(0) + push(0) + bytes([0x37])
        + push(length) + push(0) + bytes([0x20])
        + push(0) + bytes([0x55, 0x00])
    )


@pytest.fixture(scope="module")
def outcomes():
    rng = np.random.default_rng(3)
    datas = [
        bytes(rng.integers(0, 256, max(L, 1), dtype=np.uint8).tolist())[:L]
        for L in SIZES
    ]
    table = make_code_table([_sha_program(L) for L in SIZES])
    batch = make_batch(
        len(SIZES),
        code_ids=np.arange(len(SIZES)),
        calldata=datas,
        calldata_cap=1024,
        mem_cap=2048,
    )
    out, _ = run(batch, table, max_steps=64)
    return datas, out


@pytest.mark.parametrize("i", range(len(SIZES)))
def test_digest_matches_oracle(i, outcomes):
    datas, out = outcomes
    assert int(out.status[i]) == Status.STOPPED
    got = storage_dict(out, i).get(0, 0)
    assert got == int.from_bytes(keccak256(datas[i]), "big")
