"""Wide-branching ownership parity: the regime where the device
engine's breadth is structural, pinned end to end.

A wide contract (K independent calldata guards + overflow-to-branch +
ORIGIN/TIMESTAMP guards + guarded SELFDESTRUCT, corpusgen.py
`wide_contract`) forks a sequential walk ~2^K ways; branch-coverage
closure on the device needs one flip per guard direction. These tests
hold the round-5 ownership inversion to its soundness bar: the
device-owned result must report exactly the host walk's distinct
findings — and the finality/parking machinery must actually engage.
"""

import pytest

from mythril_tpu.analysis.corpus import analyze_corpus, corpus_device_prepass
from mythril_tpu.analysis.corpusgen import wide_contract


def _distinct(result):
    return sorted({(i["swc-id"], i["address"]) for i in result["issues"]})


@pytest.fixture(scope="module")
def wide_code():
    return wide_contract(3, seed=11)


@pytest.fixture(scope="module")
def host_result(wide_code):
    res = analyze_corpus(
        [(wide_code, "", "wide")],
        transaction_count=2,
        execution_timeout=90,
        create_timeout=10,
        use_device=False,
        processes=1,
    )[0]
    assert res["error"] is None
    return res


@pytest.mark.slow
def test_host_walk_finds_all_classes(host_result):
    swcs = {i["swc-id"] for i in host_result["issues"]}
    # wrap (101), selfdestruct (106), origin (115), timestamp (116 —
    # the SWC the is_prehook phase bug silently suppressed until the
    # explicit hook-phase context fixed it)
    assert swcs == {"101", "106", "115", "116"}


@pytest.mark.slow
def test_device_completes_and_matches_host(wide_code, host_result):
    out = corpus_device_prepass(
        [(wide_code, "", "wide")], budget_s=120.0, transaction_count=2
    )
    o = out.get(0)
    assert o is not None
    assert o.get("device_complete"), o.get("completeness_gates")
    device = analyze_corpus(
        [(wide_code, "", "wide")],
        transaction_count=2,
        execution_timeout=90,
        create_timeout=10,
        processes=1,
        use_device=True,  # the CPU backend runs the device engine too
        device_budget_s=120.0,
    )[0]
    assert device.get("owned"), "expected the device to own this contract"
    assert _distinct(device) == _distinct(host_result)


@pytest.mark.slow
def test_bec_contract_shape():
    """The BEC-guard fixture (corpusgen.bec_contract): the host walk
    must find the unchecked-multiplication SWC-101 and the guarded
    SWC-110 — pinning the hand-assembled jump offsets and the
    `m/y != x` branch shape the hard-solve bench races on."""
    from mythril_tpu.analysis.corpusgen import bec_contract

    res = analyze_corpus(
        [(bec_contract(), "", "bec")],
        transaction_count=1,
        execution_timeout=90,
        create_timeout=5,
        use_device=False,
        processes=1,
    )[0]
    assert res["error"] is None
    swcs = {i["swc-id"] for i in res["issues"]}
    assert {"101", "110"} <= swcs


@pytest.mark.slow
def test_corpus_run_parks_wide_contract_early(wide_code):
    """Striped beside a never-converging contract, the wide contract
    must reach per-contract finality (parked, final_for_contract) even
    though the corpus exploration keeps running."""
    from mythril_tpu.analysis.corpusgen import loop_contract

    out = corpus_device_prepass(
        [(wide_code, "", "wide"), (loop_contract(0xFF), "", "loop")],
        budget_s=60.0,
        transaction_count=2,
    )
    o = out.get(0)
    assert o is not None
    assert o.get("device_complete"), o.get("completeness_gates")
