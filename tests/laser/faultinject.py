"""Deterministic fault-injection harness for the resilience suite.

Thin, test-facing wrappers over the production injection hooks in
`mythril_tpu/support/resilience.py`: production code calls
`resilience.inject(site)` at the boundaries this harness arms, so the
fault suite exercises the EXACT code paths a real hang / device fault /
signal would take — no monkeypatching of internals, no timing races.

Sites wired into the pipeline:

- ``solver.cdcl``     — inside the watchdog-guarded native CDCL call
                        (native_sat.SolverSession.solve); a "hang"
                        action simulates a wedged native solver.
- ``device.dispatch`` — inside every attempt of the device-dispatch
                        retry ladder (resilience.retry_device_dispatch,
                        used by run.run_resilient and the explorer's
                        wave dispatch).
- ``explore.wave``    — in DeviceCorpusExplorer._dispatch_wave, before
                        the async dispatch: the "killed mid-wave"
                        point (the checkpoint flush is already on the
                        background writer).
- ``corpus.contract`` — at analyze_corpus's per-contract supervisor
                        boundary.
"""

from __future__ import annotations

import os
import signal
from contextlib import contextmanager

from mythril_tpu.support import resilience


@contextmanager
def injected(site: str, **kwargs):
    """Arm one fault for the duration of the block (always disarmed,
    even when the fault escapes as an exception)."""
    resilience.arm_fault(site, **kwargs)
    try:
        yield
    finally:
        resilience.disarm_faults()


@contextmanager
def solver_hang(delay_s: float = 2.0, grace_s: float = 0.2, times: int = 1):
    """Simulate a wedged native CDCL call: the guarded region sleeps
    past a shrunken watchdog grace, so the watchdog fires in test time
    instead of the production 30s."""
    previous = resilience.SOLVER_WATCHDOG_GRACE_S
    resilience.SOLVER_WATCHDOG_GRACE_S = grace_s
    resilience.arm_fault(
        "solver.cdcl", times=times, action="hang", delay_s=delay_s
    )
    try:
        yield
    finally:
        resilience.SOLVER_WATCHDOG_GRACE_S = previous
        resilience.disarm_faults()


@contextmanager
def device_faults(times: int = 1, skip: int = 0):
    """Fail device dispatches with a classified infrastructure fault
    (the injection raises InjectedFault at a ``device.*`` site, which
    resilience.is_device_fault classifies as retriable)."""
    resilience.arm_fault("device.dispatch", times=times, skip=skip)
    try:
        yield
    finally:
        resilience.disarm_faults()


@contextmanager
def sigterm_at(site: str, skip: int = 0):
    """Deliver a real SIGTERM to this process when `site` is next
    reached (after `skip` pass-throughs). Pair with
    resilience.graceful_shutdown() so the signal degrades the run
    instead of killing pytest."""
    resilience.arm_fault(
        site,
        times=1,
        action="call",
        skip=skip,
        fn=lambda: os.kill(os.getpid(), signal.SIGTERM),
    )
    try:
        yield
    finally:
        resilience.disarm_faults()
