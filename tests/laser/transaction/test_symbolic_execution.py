"""End-to-end LASER engine tests on small bytecode (reference test
strategy: tests/laser/transaction/)."""

import pytest

from mythril_tpu.laser.ethereum.svm import LaserEVM
from mythril_tpu.laser.ethereum.state.world_state import WorldState
from mythril_tpu.laser.ethereum.strategy.basic import BreadthFirstSearchStrategy


def wrap_runtime(runtime_hex: str) -> str:
    """Minimal creation code: CODECOPY the runtime and RETURN it."""
    runtime = bytes.fromhex(runtime_hex)
    n = len(runtime)
    assert n < 256
    creation = bytes(
        [0x60, n, 0x60, 0x0C, 0x60, 0x00, 0x39, 0x60, n, 0x60, 0x00, 0xF3]
    )
    return (creation + runtime).hex()


def run_symbolic(runtime_hex, tx_count=1, **kwargs):
    laser = LaserEVM(
        transaction_count=tx_count,
        execution_timeout=120,
        create_timeout=60,
        requires_statespace=True,
        **kwargs,
    )
    laser.sym_exec(
        creation_code=wrap_runtime(runtime_hex),
        contract_name="Test",
        world_state=WorldState(),
    )
    return laser


def test_creation_deploys_runtime():
    # runtime: PUSH1 1 PUSH1 0 SSTORE STOP
    laser = run_symbolic("6001600055600060015500")
    assert len(laser.open_states) >= 1
    deployed = [
        acc
        for ws in laser.open_states
        for acc in ws.accounts.values()
        if acc.code.bytecode != ""
    ]
    assert deployed
    assert deployed[0].code.bytecode == "6001600055600060015500"


def test_branching_on_calldata_explores_both_paths():
    # runtime: PUSH1 0 CALLDATALOAD PUSH1 8 JUMPI STOP JUMPDEST STOP
    laser = run_symbolic("600035600757005b00")
    # both the taken and fall-through paths terminate in STOP
    assert len(laser.open_states) == 2


def test_storage_write_reaches_open_state():
    laser = run_symbolic("6001600055600060015500")
    ws = laser.open_states[0]
    deployed = [a for a in ws.accounts.values() if a.code.bytecode][0]
    from mythril_tpu.laser.smt import symbol_factory

    value = deployed.storage[symbol_factory.BitVecVal(0, 256)]
    assert value.value == 1


def test_revert_path_discards_world_state():
    # runtime: PUSH1 0 PUSH1 0 REVERT
    laser = run_symbolic("60006000fd")
    assert len(laser.open_states) == 0


def test_multi_transaction_execution():
    # a contract whose storage counts calls: SLOAD 0, +1, SSTORE 0
    laser = run_symbolic("60005460010160005500", tx_count=2)
    assert len(laser.open_states) >= 1


def test_bfs_strategy_works():
    laser = run_symbolic(
        "600035600757005b00", strategy=BreadthFirstSearchStrategy
    )
    assert len(laser.open_states) == 2


def test_cfg_is_recorded():
    laser = run_symbolic("600035600757005b00")
    assert len(laser.nodes) > 0
    assert len(laser.edges) > 0
