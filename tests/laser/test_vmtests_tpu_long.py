"""Long-running VMTests stragglers (forever-loop gas exhaustion).

These four cases need ~25k+ loop iterations to burn their gas budget —
trivial on TPU (~24s incl. compile), impractical on the CPU test mesh,
so this module runs only on a real TPU backend. With it, every loaded
VMTests case passes: 531/531.
"""

import jax
import pytest

if jax.default_backend() == "cpu":  # pragma: no cover
    pytest.skip(
        "forever-loop cases need TPU-scale step budgets", allow_module_level=True
    )

from mythril_tpu.laser.conformance import load_vmtests, run_cases


def test_forever_out_of_gas_cases():
    cases, _ = load_vmtests()
    targets = [c for c in cases if "foreverOutOfGas" in c.name]
    assert len(targets) == 4
    verdicts = run_cases(targets, max_steps=120000)
    assert all(v == "pass" for v in verdicts.values()), verdicts
