"""Round-5 device evidence + ownership: kernel banks, synthesis,
poisoned storage, and the completeness gate.

Everything here runs on the CPU backend (conftest pins it): the
evidence machinery is backend-agnostic, and tiny hand-assembled
contracts keep the waves fast.
"""

import pytest

from mythril_tpu.analysis.corpus import _outcome_owns, analyze_corpus
from mythril_tpu.analysis.evidence import evidence_issues
from mythril_tpu.analysis.prepass import reset_proven, witness_issues
from mythril_tpu.ethereum.evmcontract import EVMContract
from mythril_tpu.laser.batch.explore import DeviceSymbolicExplorer

ADDR = 0x901D573B8CE8C997DE5F19173C32D966B4FA55FE

#: PUSH1 5; CALLDATALOAD(0); SUB (wraps when cd < 5); SSTORE slot 0;
#: ORIGIN == CALLDATALOAD(0) -> JUMPI; STOP
WRAP_AND_ORIGIN = (
    "6005" "6000" "35" "03" "600055" "32" "600035" "14" "6011" "57" "00"
    "5b00"
)

#: value-bearing CALL to a calldata-derived target:
#: CALL(gas=0xffff, to=cd[0..31], value=1, ...); STOP
CALL_TO_CALLDATA = "6000600060006000600160003561fffff100"

#: arithmetic on INITIAL STORAGE: sload(0) + calldataload(0) stored
#: back — wraps only under a poisoned start state
STORAGE_ADD = "60005460003501600055" + "00"


def explore(code_hex, **kw):
    kw.setdefault("lanes", 8)
    kw.setdefault("waves", 6)
    kw.setdefault("steps_per_wave", 128)
    kw.setdefault("transaction_count", 1)
    ex = DeviceSymbolicExplorer(code_hex, **kw)
    return ex, ex.run()


def test_wrap_event_banked_and_synthesized():
    _, out = explore(WRAP_AND_ORIGIN)
    recs = [r for r in out["evidence"] if r["class"] == "wrap"]
    assert recs and recs[0]["pc"] == 5 and recs[0]["op"] == "subtraction"
    reset_proven()
    issues = evidence_issues(
        EVMContract(code=WRAP_AND_ORIGIN, name="w"), out, ADDR
    )
    wraps = [i for i in issues if i.swc_id == "101"]
    assert wraps and wraps[0].address == 5
    assert wraps[0].title == "Integer Arithmetic Bugs"
    # the witness replays: the banked input IS the transaction
    steps = wraps[0].transaction_sequence["steps"]
    assert steps and int(steps[-1]["input"][2:10] or "0", 16) < 5


def test_origin_provenance_survives_mixed_opacity():
    _, out = explore(WRAP_AND_ORIGIN)
    env = [r for r in out["evidence"] if r["class"] == "env"]
    assert env and env[0]["swc"] == "115" and env[0]["pc"] == 16


def test_call_steering_confirms_attacker_target():
    """Wave 1 banks a tainted-target call; the steering witness seeds
    a lane that concretely calls the attacker with value."""
    _, out = explore(CALL_TO_CALLDATA, waves=4)
    call = [r for r in out["evidence"] if r["class"] == "call"][0]
    assert call["to_attacker"] and call["value_to_attacker"]
    assert call["unchecked"]  # no branch after the call
    reset_proven()
    issues = evidence_issues(
        EVMContract(code=CALL_TO_CALLDATA, name="c"), out, ADDR
    )
    swcs = {i.swc_id for i in issues}
    assert {"104", "105", "107"} <= swcs


def test_poisoned_storage_exhibits_storage_dependent_wrap():
    """sload(0) + cd wraps only under the synthetic MAX start state;
    the witness must DECLARE the poisoned storage it assumed."""
    _, out = explore(STORAGE_ADD, waves=8)
    wraps = [r for r in out["evidence"] if r["class"] == "wrap"]
    assert wraps, "poisoned carry never exhibited the wrap"
    assert wraps[0].get("initial_storage"), "witness must declare poison"
    reset_proven()
    issues = witness_issues(EVMContract(code=STORAGE_ADD, name="p"), out, ADDR)
    w = [i for i in issues if i.swc_id == "101"][0]
    accounts = w.transaction_sequence["initialState"]["accounts"]
    assert "0x0" in accounts[hex(ADDR)]["storage"]


def test_outcome_owns_requires_final_and_complete():
    assert not _outcome_owns(None)
    assert not _outcome_owns({"device_complete": False, "stats": {}})
    assert not _outcome_owns(
        {"device_complete": True, "stats": {"partial": True}}
    )
    assert _outcome_owns({"device_complete": True, "stats": {}})


def test_ownership_end_to_end_matches_host_walk():
    """analyze_corpus with ownership: the owned result's distinct
    findings equal the host-only walk's on the same contract."""
    rows = [(WRAP_AND_ORIGIN, "", "w")]
    dev = analyze_corpus(
        rows,
        transaction_count=1,
        execution_timeout=30,
        create_timeout=10,
        use_device=True,
        processes=1,
    )
    host = analyze_corpus(
        rows,
        transaction_count=1,
        execution_timeout=30,
        create_timeout=10,
        use_device=False,
        processes=1,
    )
    fp = lambda res: {  # noqa: E731
        (i["swc-id"], i["address"]) for i in res[0]["issues"]
    }
    assert dev[0].get("owned"), "device-complete contract must be owned"
    assert fp(dev) == fp(host)


def test_incomplete_contract_falls_back_to_host_walk():
    """A contract whose device exploration degrades (memory cap) is
    NOT owned: the host walk carries it."""
    from mythril_tpu.analysis.corpusgen import degrader_contract

    # past even the roomy 16384-byte cap, so the demotion happens in
    # every prepass configuration
    rows = [(degrader_contract(0x5000), "", "d")]
    res = analyze_corpus(
        rows,
        transaction_count=1,
        execution_timeout=30,
        create_timeout=10,
        use_device=True,
        device_budget_s=20.0,
        processes=1,
    )
    assert not res[0].get("owned")
    assert {i["swc-id"] for i in res[0]["issues"]} == {"110"}
