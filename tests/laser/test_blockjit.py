"""Block-level JIT (ISSUE 13): block-summary goldens, the per-pc
block-program table, blockjit-vs-generic differentials (concrete +
symbolic, incl. mid-block OOG replay and the taint/wrap evidence
paths), kernel-cache block-program keys, the unified fuse/block
decomposition, and --no-blockjit parity.

The acceptance bar: blockjit and fuse-only/generic kernels produce
bit-identical final states on halting contracts and identical issue
sets on the fault suite (the slow sweep extends that to every module
positive fixture); a block containing calls/storage/memory/env ops is
never lowered (attributed fallback, never silent mis-execution).
Everything runs on CPU JAX.
"""

import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mythril_tpu.analysis.corpusgen import deadweight_contract
from mythril_tpu.disassembler import asm
from mythril_tpu.laser.batch import blockjit as bj
from mythril_tpu.laser.batch import specialize as sp
from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer
from mythril_tpu.laser.batch.run import run
from mythril_tpu.laser.batch.state import Status, make_batch, make_code_table
from mythril_tpu.laser.batch.step import PhaseSet
from mythril_tpu.laser.batch.symbolic import make_sym_batch, sym_run
from mythril_tpu.support.support_args import args as support_args

pytestmark = pytest.mark.blockjit


@pytest.fixture(autouse=True)
def _blockjit_on():
    """The suite tests the feature itself: re-enable the flags the
    test conftest turns off for tier-1 wall-time."""
    before = (support_args.specialize, support_args.blockjit)
    support_args.specialize = True
    support_args.blockjit = True
    yield
    support_args.specialize, support_args.blockjit = before


#: the fault-suite fixtures (same shapes/seeds as the pipeline and
#: specialize suites)
WRITER = "6001600055600060015500"
BRANCHER = "600035600757005b600160005500"
KILLABLE = "33ff"
GATED = "60003560f81c604214600d57005b605560aa01506001600055 00".replace(" ", "")
#: a halting pure-ALU chain: one lowerable block ending in STOP
ALUCHAIN = "6001600302600701605519168015145000"
#: an ALU block jumping into a storage-writing block: the lowered
#: block feeds the unlowered one through the stack
ALUWRITE = bytes(
    [0x60, 0x01, 0x60, 0x02, 0x01, 0x60, 0x09, 0x56, 0x00,
     0x5B, 0x60, 0x00, 0x55, 0x00]
).hex()

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _module_fixture_codes():
    path = os.path.join(
        _REPO, "tests", "analysis", "test_module_positive_fixtures.py"
    )
    spec = importlib.util.spec_from_file_location("_module_fixtures", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return [code for code, _swc in mod.FIXTURES.values()]


# -- block summaries (goldens) ------------------------------------------------
def test_block_summary_golden_deadweight():
    """Every lowering decision on the deadweight fixture pinned:
    counts, densities, and the per-reason fallback attribution."""
    code = bytes.fromhex(deadweight_contract(0))
    stats = bj.block_stats(code)
    assert stats["blocks_total"] == 10
    assert stats["blocks_lowered"] == 3
    assert stats["blocks_unlowered"] == 7
    assert stats["fallback_reasons"] == {
        "tiny": 5, "env": 1, "storage": 1
    }
    # fallbacks are attributed, never silent: every unlowered block
    # carries a reason
    blocks = bj.summarize_blocks(code)
    assert all(b.reason != "ok" for b in blocks.values() if not b.lowerable)
    assert all(b.reason == "ok" for b in blocks.values() if b.lowerable)


def test_block_summary_stack_effect_and_gas():
    """Net stack effect, minimum entry stack, and static gas bounds of
    a known straight-line block."""
    # PUSH1 1; PUSH1 3; MUL; PUSH1 7; ADD; ... STOP — one block
    code = bytes.fromhex(ALUCHAIN)
    blocks = bj.summarize_blocks(code)
    assert list(blocks) == [0]
    blk = blocks[0]
    assert blk.lowerable and blk.reason == "ok"
    # PUSH1(+1) x5, MUL/ADD/NOT/AND/EQ/(DUP1,ISZERO...) net to 0 with
    # the POPs/STOP — recompute independently from the disassembly
    net = 0
    need = 0
    gas_min = gas_max = 0
    from mythril_tpu.support.opcodes import OPCODES

    for ins in asm.disassemble(code):
        _b, pops, pushes, gmin, gmax = OPCODES[ins.opcode]
        need = max(need, pops - net)
        net += pushes - pops
        gas_min += gmin
        gas_max += gmax
    assert blk.net_sp == net
    assert blk.min_sp == need == 0
    assert blk.gas_min == gas_min and blk.gas_max == gas_max
    assert not blk.touches_mem and not blk.touches_storage
    assert not blk.has_call


def test_block_summary_golden_computed_jump():
    """The computed-jump shape (tests/analysis/test_static_cfg.py):
    with the static summary the dataflow pass resolves the jump and
    the ALU block lowers; without it the peephole cannot see the
    target and the block falls back as unresolved-jump — the dataflow
    consumption the tentpole names."""
    from mythril_tpu.analysis.static import analyze_bytecode

    code = asm.assemble(
        """
        PUSH1 0x55
        PUSH1 0x03
        DUP1
        ADD
        PUSH1 0x06
        ADD
        SWAP1
        POP
        JUMP
        JUMPDEST
        STOP
        """
    )
    summary = analyze_bytecode(code)
    with_summary = bj.summarize_blocks(code, summary)
    without = bj.summarize_blocks(code)
    assert with_summary[0].lowerable
    assert not without[0].lowerable
    assert without[0].reason == "unresolved-jump"


def test_fallback_reason_categories():
    cases = {
        "call": "60006000600060006000600061deadf100",  # CALL
        "storage": WRITER,
        "memory": "6001600052600051500000",  # MSTORE/MLOAD
        "env": KILLABLE,  # CALLER
    }
    for want, code_hex in cases.items():
        stats = bj.block_stats(bytes.fromhex(code_hex))
        assert want in stats["fallback_reasons"], (want, stats)


# -- the block-program table (goldens) ---------------------------------------
def test_block_row_golden():
    code = bytes.fromhex(ALUCHAIN)
    row = bj.build_block_row(code, 32)
    # head at pc 0 (PUSH1), interiors at every lowered instruction,
    # immediates never marked, STOP (terminator) unmarked
    assert row[0] == bj.ROW_HEAD
    assert row[1] == 0  # PUSH immediate
    interiors = {2, 4, 5, 7, 8, 10, 11, 12, 13, 14, 15}
    assert {int(i) for i in np.flatnonzero(row == bj.ROW_BODY)} == interiors
    assert row[16] == 0  # STOP


def test_block_row_keeps_fuse_marks_in_unlowered_blocks():
    """PR-6 superblock fusion rides along: fusible pcs inside blocks
    blockjit cannot lower keep their ROW_FUSE mark, so the substeps
    still advance stack-shuffle runs there."""
    row = bj.build_block_row(bytes.fromhex(WRITER), 32)
    # WRITER's single block has SSTORE -> unlowered, but the PUSHes
    # stay fusible
    assert {int(i) for i in np.flatnonzero(row == bj.ROW_FUSE)} == {0, 2, 5, 7}
    assert not (row >= bj.ROW_BODY).any()


def test_block_depth_profitability_gate():
    assert bj.block_depth_for(bytes.fromhex(ALUCHAIN)) == bj.BLOCK_DEPTH
    assert bj.block_depth_for(bytes.fromhex(WRITER)) == 0  # nothing lowers
    assert bj.block_depth_for(b"") == 0
    # deadweight: lowered blocks exist but density sits under the floor
    stats = bj.block_stats(bytes.fromhex(deadweight_contract(0)))
    assert stats["lowered_density"] < bj.BLOCK_DENSITY_MIN
    assert bj.block_depth_for(bytes.fromhex(deadweight_contract(0))) == 0


# -- unified decomposition (the satellite) -----------------------------------
def test_fuse_rows_agree_with_cfg_decomposition():
    """build_fuse_row marks the same pcs from the CFG instruction list
    as from the raw sweep (one instruction alignment, two walks)."""
    from mythril_tpu.analysis.static import analyze_bytecode

    for code_hex in (WRITER, BRANCHER, GATED, ALUCHAIN, ALUWRITE):
        code = bytes.fromhex(code_hex)
        summary = analyze_bytecode(code)
        np.testing.assert_array_equal(
            sp.build_fuse_row(code, 64, summary),
            sp.build_fuse_row(code, 64),
            code_hex,
        )


def test_fuse_runs_break_at_block_boundaries_with_summary():
    """With a summary, fuse runs are CFG-block-bounded: a run never
    crosses a JUMPDEST leader, so fusion and blockjit agree on block
    boundaries. The sweep (no summary) keeps the legacy
    run-spans-blocks behavior."""
    from mythril_tpu.analysis.static import analyze_bytecode

    # PUSH1 1; PUSH1 5; JUMPI-able? simpler: straight line into a
    # JUMPDEST-led block: PUSH1 1; PUSH1 2; JUMPDEST...: build code
    # where a fusible run crosses a leader
    code = asm.assemble(
        """
        PUSH1 0x01
        PUSH1 0x04
        JUMP
        JUMPDEST
        PUSH1 0x02
        POP
        POP
        STOP
        """
    )
    summary = analyze_bytecode(code)
    runs_sweep = sp.fuse_run_lengths(code)
    runs_cfg = sp.fuse_run_lengths(code, summary)
    # the sweep sees one long run across JUMP's pc 4 leader; the CFG
    # decomposition splits at the JUMPDEST block start
    assert any(start == 5 for start, _n in runs_cfg)
    assert sum(n for _s, n in runs_sweep) >= sum(n for _s, n in runs_cfg)


# -- kernel equivalence -------------------------------------------------------
_EQ_CODES = (ALUCHAIN, ALUWRITE, WRITER, BRANCHER, KILLABLE)


def _eq_setup():
    codes = [bytes.fromhex(c) for c in _EQ_CODES]
    table = make_code_table(codes)
    cap = table.ops.shape[1] - 33
    blk = jnp.asarray(bj.build_block_table(codes, cap))
    phases = sp.union_phases(
        [
            sp.phases_for(
                sp.signature_for(c),
                fuse=sp.fuse_profitable(c),
                block_depth=bj.block_depth_for(c),
            )
            for c in codes
        ]
    )
    assert phases.block_depth == bj.BLOCK_DEPTH
    batch = make_batch(
        10, code_ids=[0, 1, 2, 3, 4] * 2, calldata=[b"\x42" * 8] * 10
    )
    return table, blk, phases, batch


def _assert_trees_equal(a, b):
    for i, (x, y) in enumerate(
        zip(jax.tree.flatten(a)[0], jax.tree.flatten(b)[0])
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), str(i))


def test_blockjit_concrete_kernel_matches_generic():
    table, blk, phases, batch = _eq_setup()
    g_out, _ = run(batch, table, max_steps=64)
    kern = sp.kernel_cache().get(phases)
    s_out, _steps, subs, blocks = kern.run(batch, table, blk, max_steps=64)
    assert int(subs) > 0  # block substeps actually advanced work
    assert int(blocks) > 0  # whole lowered blocks were entered
    _assert_trees_equal(g_out, s_out)


def test_blockjit_sym_kernel_matches_generic():
    table, blk, phases, batch = _eq_setup()
    g_out, _s, _a = sym_run(make_sym_batch(batch), table, max_steps=64)
    kern = sp.kernel_cache().get(phases)
    s_out, _s2, _a2, subs, blocks = kern.sym_run(
        make_sym_batch(batch), table, blk, max_steps=64
    )
    assert int(subs) > 0 and int(blocks) > 0
    _assert_trees_equal(g_out, s_out)


def test_blockjit_sym_taint_and_wrap_defer_to_full_step():
    """The two subtle symbolic paths, pinned under IDENTICAL phase
    pruning (one compile pair — isolates the blockjit delta):

    - ALU over calldata-tainted operands inside a lowered block: the
      substep must skip so the full sym step appends the arena node —
      the expression arena is bit-identical;
    - a concretely-wrapping ADD inside a lowered block: the substep
      must skip so the full sym step banks the wrap event — the
      evidence banks are bit-identical."""
    taint = bytes(
        [0x60, 0x00, 0x35, 0x60, 0x08, 0x56, 0x00, 0x00,
         0x5B, 0x60, 0x03, 0x02, 0x60, 0x07, 0x01, 0x80, 0x18, 0x50,
         0x00]
    )
    wrap = bytes([0x7F] + [0xFF] * 32 + [0x60, 0x02, 0x01, 0x50, 0x00])
    codes = [taint, wrap]
    table = make_code_table(codes)
    cap = table.ops.shape[1] - 33
    blk = jnp.asarray(bj.build_block_table(codes, cap))
    fuse = jnp.asarray(sp.build_fuse_table(codes, cap))
    base = sp.union_phases(
        [
            sp.phases_for(
                sp.signature_for(c), fuse=sp.fuse_profitable(c)
            )
            for c in codes
        ]
    )
    bjp = base._replace(
        block_depth=max(bj.block_depth_for(c) for c in codes)
    )
    assert bjp.block_depth > 0
    batch = make_batch(
        4,
        code_ids=[0, 0, 1, 1],
        calldata=[b"\xff" * 36, b"\x01" + b"\x00" * 35, b"", b""],
    )
    g_out, *_ = sp.kernel_cache().get(base).sym_run(
        make_sym_batch(batch), table, fuse, max_steps=64
    )
    s_out, _st, _a, _subs, blocks = sp.kernel_cache().get(bjp).sym_run(
        make_sym_batch(batch), table, blk, max_steps=64
    )
    assert int(blocks) > 0
    assert int(np.asarray(g_out.ar_count)) > 0  # taint nodes created
    assert int(np.asarray(g_out.ev_cnt).sum()) > 0  # wrap banked
    _assert_trees_equal(g_out, s_out)


def test_midblock_oog_replayed_by_generic_step():
    """A gas budget that dies mid-lowered-block: the substep skips the
    unaffordable op and the next full step produces the exact generic
    ERR_OOG verdict."""
    codes = [bytes.fromhex(ALUCHAIN)]
    table = make_code_table(codes)
    cap = table.ops.shape[1] - 33
    blk = jnp.asarray(bj.build_block_table(codes, cap))
    phases = sp.phases_for(
        sp.signature_for(codes[0]),
        fuse=sp.fuse_profitable(codes[0]),
        block_depth=bj.block_depth_for(codes[0]),
    )
    batch = make_batch(
        2, code_ids=[0, 0], calldata=[b""] * 2, gas_budget=20
    )
    g_out, _ = run(batch, table, max_steps=64)
    kern = sp.kernel_cache().get(phases)
    s_out, _steps, _subs, _blocks = kern.run(
        batch, table, blk, max_steps=64
    )
    assert (np.asarray(g_out.status) == Status.ERR_OOG).all()
    _assert_trees_equal(g_out, s_out)


def test_pruned_opcode_parks_for_degrade_inside_lowered_block():
    """The safety net holds THROUGH substeps: an op whose phase the
    kernel pruned is never advanced by a block substep — the lane
    parks AT the instruction with UNSUPPORTED exactly like the full
    step's degrade."""
    code = bytes.fromhex(ALUCHAIN)
    codes = [code]
    table = make_code_table(codes)
    cap = table.ops.shape[1] - 33
    blk = jnp.asarray(bj.build_block_table(codes, cap))
    wrong = sp.phases_for(
        sp.signature_for(code), fuse=False,
        block_depth=bj.block_depth_for(code),
    )._replace(arith=False)  # MUL/ADD's phase wrongly pruned
    batch = make_batch(2, code_ids=[0, 0], calldata=[b""] * 2)
    kern = sp.kernel_cache().get(wrong)
    out, _steps, _subs, _blocks = kern.run(batch, table, blk, max_steps=32)
    assert (np.asarray(out.status) == Status.UNSUPPORTED).all()
    assert (np.asarray(out.pc) == 4).all()  # parked AT the MUL


# -- the compile cache: block-program keys -----------------------------------
def test_kernel_cache_block_keys_are_distinct_buckets():
    cache = sp.KernelCache(capacity=4)
    base = PhaseSet(sha3=False)
    blocky = base._replace(block_depth=bj.BLOCK_DEPTH)
    k0 = cache.get(base)
    k1 = cache.get(blocky)
    assert k0 is not k1  # block-program keys split the bucket
    assert cache.get(blocky) is k1  # and hit stably
    stats = cache.stats()
    assert stats["misses"] == 2 and stats["hits"] == 1


def test_kernel_cache_block_key_pin_and_evict():
    cache = sp.KernelCache(capacity=2)
    pinned = cache.acquire(PhaseSet(block_depth=bj.BLOCK_DEPTH))
    cache.get(PhaseSet(exp=False, block_depth=bj.BLOCK_DEPTH))
    cache.get(PhaseSet(div=False, block_depth=bj.BLOCK_DEPTH))
    stats = cache.stats()
    assert stats["evictions"] == 1
    assert stats["pinned"] == 1
    assert cache.get(PhaseSet(block_depth=bj.BLOCK_DEPTH)) is pinned
    cache.release(pinned)


def test_service_code_cache_feed_carries_block_row():
    """The satellite: per-code block rows are built ONCE into the
    CodeCache specialization feed (keyed by codehash) instead of per
    wave — and a --no-blockjit engine keeps depth-0 buckets."""
    from mythril_tpu.service.engine import CodeCache

    cache = CodeCache(code_cap=64, capacity=4)
    code = bytes.fromhex(ALUCHAIN)
    feed = cache.spec_for(code)
    assert feed is not None
    assert feed["phases"].block_depth == bj.BLOCK_DEPTH
    assert feed["block_row"] is not None
    assert feed["block_row"][0] == bj.ROW_HEAD
    hits_before = cache.hits
    assert cache.spec_for(code) is feed  # cached, not rebuilt
    assert cache.hits == hits_before + 1

    off = CodeCache(code_cap=64, capacity=4, blockjit=False)
    feed_off = off.spec_for(code)
    assert feed_off["phases"].block_depth == 0
    assert feed_off["block_row"] is None


# -- the explorer differential (acceptance criterion) ------------------------
def _fingerprint(contract):
    return (
        tuple(map(tuple, contract["covered_branches"])),
        {
            kind: tuple(sorted(t["pc"] for t in bucket))
            for kind, bucket in contract["triggers"].items()
        },
        tuple(sorted((e["class"], e["pc"]) for e in contract["evidence"])),
    )


def _explore(codes, blockjit, **kw):
    kw.setdefault("lanes_per_contract", 8)
    kw.setdefault("waves", 3)
    kw.setdefault("steps_per_wave", 64)
    kw.setdefault("transaction_count", 1)
    before = support_args.blockjit
    support_args.blockjit = blockjit
    try:
        ex = DeviceCorpusExplorer(codes, specialize=True, **kw)
        return ex, ex.run()
    finally:
        support_args.blockjit = before


def test_differential_issue_sets_fault_suite():
    codes = [KILLABLE, WRITER, BRANCHER, GATED, ALUWRITE]
    _, on = _explore(codes, True, seed=7)
    _, off = _explore(codes, False, seed=7)
    for s, g in zip(on["contracts"], off["contracts"]):
        assert _fingerprint(s) == _fingerprint(g)
    assert on["stats"]["blockjit_steps"] > 0
    assert on["stats"]["blockjit_blocks"] > 0
    assert on["stats"]["blockjit_fallbacks"] > 0  # attributed, not silent
    assert off["stats"]["blockjit_steps"] == 0
    assert off["stats"]["blockjit_blocks"] == 0
    # the fuse path still runs when blockjit is off
    assert off["stats"]["spec_fused_steps"] > 0
    # a blockjit wave never double-counts into the fuse counter
    assert on["stats"]["spec_fused_steps"] == 0
    # and the differential is not trivially empty
    assert "selfdestruct" in on["contracts"][0]["triggers"]


def test_no_blockjit_env_var_keeps_fuse_only_buckets():
    """MYTHRIL_NO_BLOCKJIT wins over the flag bag: the explorer's
    union bucket stays at block_depth 0 (init-time decision, no wave
    dispatched)."""
    os.environ["MYTHRIL_NO_BLOCKJIT"] = "1"
    try:
        assert not bj.blockjit_enabled()
        ex = DeviceCorpusExplorer(
            [ALUWRITE], lanes_per_contract=4, waves=1,
            steps_per_wave=16, transaction_count=1, specialize=True,
        )
        assert ex.kernel_phases is not None
        assert ex.kernel_phases.block_depth == 0
    finally:
        del os.environ["MYTHRIL_NO_BLOCKJIT"]
    assert bj.blockjit_enabled()
    ex = DeviceCorpusExplorer(
        [ALUWRITE], lanes_per_contract=4, waves=1,
        steps_per_wave=16, transaction_count=1, specialize=True,
    )
    assert ex.kernel_phases.block_depth == bj.BLOCK_DEPTH


def test_merge_policy_covers_blockjit_counters():
    from mythril_tpu.laser.batch.explore import MERGE_POLICY

    for field in ("blockjit_steps", "blockjit_blocks",
                  "blockjit_fallbacks"):
        assert MERGE_POLICY[field] == "sum"


@pytest.mark.slow
def test_differential_issue_sets_module_fixtures():
    """Every detection module's positive-fixture contract explores to
    the same coverage/trigger/evidence fingerprint with blockjit on
    and off (the full 14-fixture sweep — slow tier)."""
    codes = _module_fixture_codes()
    _, on = _explore(codes, True, seed=11, waves=2)
    _, off = _explore(codes, False, seed=11, waves=2)
    for s, g in zip(on["contracts"], off["contracts"]):
        assert _fingerprint(s) == _fingerprint(g)
