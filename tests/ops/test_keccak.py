"""keccak-256: host (python + native) and device implementations agree
with each other and with published EVM vectors."""

import os
import subprocess

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

import jax
import jax.numpy as jnp

from mythril_tpu.ops import keccak as dkeccak
from mythril_tpu.support import keccak as hkeccak

# Published EVM keccak-256 vectors (Ethereum ecosystem ground truth)
VECTORS = {
    # the EVM empty code hash, hardcoded across the Ethereum ecosystem
    b"": "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470",
    b"abc": "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45",
    b"transfer(address,uint256)":
        "a9059cbb2ab09eb219583f4a59a5d0623ade346d962bcd4e46b11da047c9049b",
}


@pytest.mark.parametrize("msg,digest", VECTORS.items())
def test_host_vectors(msg, digest):
    assert hkeccak._keccak256_py(msg).hex() == digest


def test_long_input_multiblock():
    msg = bytes(range(256)) * 3  # several rate blocks
    d = hkeccak._keccak256_py(msg)
    assert len(d) == 32
    # block-boundary lengths exercise the padding edge (135/136 bytes)
    for n in (134, 135, 136, 137, 271, 272):
        assert len(hkeccak._keccak256_py(bytes(n))) == 32


def test_selector():
    assert hkeccak.function_selector("transfer(address,uint256)").hex() == "a9059cbb"


def test_native_matches_python():
    native_dir = os.path.join(os.path.dirname(hkeccak.__file__), "..", "native")
    subprocess.run(["make", "-s", "-C", native_dir], check=True)
    hkeccak._native = None  # force reload
    lib = hkeccak._load_native()
    assert lib, "native library should build and load"
    rng = np.random.default_rng(1)
    for n in (0, 1, 31, 32, 64, 135, 136, 137, 500):
        msg = bytes(rng.integers(0, 256, size=n, dtype=np.uint8).tolist())
        assert hkeccak.keccak256(msg) == hkeccak._keccak256_py(msg)


@pytest.mark.parametrize("n", [0, 1, 31, 32, 64, 135, 136, 137, 300])
def test_device_matches_host_fixed_lengths(n):
    rng = np.random.default_rng(n)
    msg = bytes(rng.integers(0, 256, size=n, dtype=np.uint8).tolist())
    arr = jnp.asarray(np.frombuffer(msg, dtype=np.uint8))
    got = bytes(np.asarray(jax.jit(dkeccak.keccak256)(arr)).tolist())
    assert got == hkeccak._keccak256_py(msg)


def test_device_batched():
    rng = np.random.default_rng(2)
    msgs = rng.integers(0, 256, size=(32, 64), dtype=np.uint8)
    out = jax.jit(dkeccak.keccak256)(jnp.asarray(msgs))
    out = np.asarray(out)
    for i in range(0, 32, 5):
        assert bytes(out[i].tolist()) == hkeccak._keccak256_py(bytes(msgs[i].tolist()))


def test_device_word_output():
    from mythril_tpu.ops import u256

    msg = jnp.zeros((32,), dtype=jnp.uint8)
    w = dkeccak.keccak256_word(msg)
    expect = hkeccak.keccak256_int(bytes(32))
    assert u256.to_int(w) == expect
