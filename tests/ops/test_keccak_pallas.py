"""Pallas keccak-f kernel: bit-exact against the XLA path.

Runs only on a real TPU backend: pallas interpret mode on CPU takes
minutes for 24 unrolled rounds, so the CPU suite skips this module.
(Verified on TPU v5e: bit-exact at N=4096, kernel-time parity with the
XLA path.)
"""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

if jax.default_backend() == "cpu":  # pragma: no cover
    pytest.skip(
        "pallas kernel test needs a TPU backend (interpret mode too slow)",
        allow_module_level=True,
    )

from mythril_tpu.ops.keccak import keccak_f
from mythril_tpu.ops.keccak_pallas import keccak_f_pallas


def test_pallas_keccak_matches_xla():
    rng = np.random.default_rng(42)
    lo = jnp.asarray(rng.integers(0, 2**32, (1024, 25), dtype=np.uint32))
    hi = jnp.asarray(rng.integers(0, 2**32, (1024, 25), dtype=np.uint32))
    ref_lo, ref_hi = keccak_f(lo, hi)
    pal_lo, pal_hi = keccak_f_pallas(lo, hi)
    assert jnp.array_equal(ref_lo, pal_lo)
    assert jnp.array_equal(ref_hi, pal_hi)


def test_pallas_keccak_zero_state():
    lo = jnp.zeros((1, 25), dtype=jnp.uint32)
    hi = jnp.zeros((1, 25), dtype=jnp.uint32)
    ref_lo, _ = keccak_f(lo, hi)
    pal_lo, _ = keccak_f_pallas(lo, hi)
    assert jnp.array_equal(ref_lo, pal_lo)
    assert int(pal_lo[0, 0]) != 0
