"""Property tests: u256 limb arithmetic vs python-int EVM semantics.

Python ints are the spec oracle, mirroring the reference's reliance on
z3/py ints for arithmetic semantics (reference:
mythril/laser/ethereum/instructions.py arithmetic handlers).
"""

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

import jax
import jax.numpy as jnp

from mythril_tpu.ops import u256

M = 1 << 256
HALF = 1 << 255

u256_ints = st.one_of(
    st.integers(min_value=0, max_value=M - 1),
    st.sampled_from(
        [0, 1, 2, M - 1, M - 2, HALF, HALF - 1, HALF + 1, (1 << 128) - 1, 1 << 128]
    ),
)


def as_signed(x):
    return x - M if x >= HALF else x


def roundtrip(x):
    return u256.to_int(u256.from_int(x))


# jit once per op so hypothesis examples re-run from the compile cache
J = {
    name: jax.jit(getattr(u256, name))
    for name in [
        "add", "sub", "mul", "udiv", "urem", "sdiv", "srem", "ult", "eq",
        "slt", "bit_and", "bit_or", "bit_xor", "bit_not", "shl", "lshr",
        "ashr", "addmod", "mulmod", "exp", "byte_op", "signextend",
        "bytes_to_word", "word_to_bytes",
    ]
}


@given(u256_ints)
def test_roundtrip(x):
    assert roundtrip(x) == x


def _binop(fn, a, b):
    fn = J.get(getattr(fn, "__name__", None), fn)
    return u256.to_int(fn(jnp.asarray(u256.from_int(a)), jnp.asarray(u256.from_int(b))))


@settings(deadline=None, max_examples=60)
@given(u256_ints, u256_ints)
def test_add_sub_mul(a, b):
    assert _binop(u256.add, a, b) == (a + b) % M
    assert _binop(u256.sub, a, b) == (a - b) % M
    assert _binop(u256.mul, a, b) == (a * b) % M


@settings(deadline=None, max_examples=40)
@given(u256_ints, u256_ints)
def test_divmod(a, b):
    q = _binop(u256.udiv, a, b)
    r = _binop(u256.urem, a, b)
    if b == 0:
        assert q == 0 and r == 0
    else:
        assert q == a // b and r == a % b


@settings(deadline=None, max_examples=40)
@given(u256_ints, u256_ints)
def test_signed_divmod(a, b):
    sa, sb = as_signed(a), as_signed(b)
    q = _binop(u256.sdiv, a, b)
    r = _binop(u256.srem, a, b)
    if sb == 0:
        assert q == 0 and r == 0
    else:
        expect_q = abs(sa) // abs(sb)
        if (sa < 0) != (sb < 0):
            expect_q = -expect_q
        expect_r = abs(sa) % abs(sb)
        if sa < 0:
            expect_r = -expect_r
        assert q == expect_q % M
        assert r == expect_r % M


def test_sdiv_min_by_minus_one():
    assert _binop(u256.sdiv, HALF, M - 1) == HALF


@settings(deadline=None, max_examples=60)
@given(u256_ints, u256_ints)
def test_compare(a, b):
    av, bv = jnp.asarray(u256.from_int(a)), jnp.asarray(u256.from_int(b))
    assert bool(J["ult"](av, bv)) == (a < b)
    assert bool(J["eq"](av, bv)) == (a == b)
    assert bool(J["slt"](av, bv)) == (as_signed(a) < as_signed(b))


@settings(deadline=None, max_examples=60)
@given(u256_ints, u256_ints)
def test_bitwise(a, b):
    assert _binop(u256.bit_and, a, b) == a & b
    assert _binop(u256.bit_or, a, b) == a | b
    assert _binop(u256.bit_xor, a, b) == a ^ b
    av = jnp.asarray(u256.from_int(a))
    assert u256.to_int(J["bit_not"](av)) == (~a) % M


@settings(deadline=None, max_examples=60)
@given(u256_ints, st.integers(min_value=0, max_value=300))
def test_shifts(a, s):
    av = jnp.asarray(u256.from_int(a))
    sv = jnp.uint32(s)
    assert u256.to_int(J["shl"](av, sv)) == ((a << s) % M if s < 256 else 0)
    assert u256.to_int(J["lshr"](av, sv)) == (a >> s if s < 256 else 0)
    sa = as_signed(a)
    expect_sar = sa >> s if s < 256 else (-1 if sa < 0 else 0)
    assert u256.to_int(J["ashr"](av, sv)) == expect_sar % M


@settings(deadline=None, max_examples=30)
@given(u256_ints, u256_ints, u256_ints)
def test_addmod_mulmod(a, b, m):
    av, bv, mv = (jnp.asarray(u256.from_int(x)) for x in (a, b, m))
    am = u256.to_int(J["addmod"](av, bv, mv))
    mm = u256.to_int(J["mulmod"](av, bv, mv))
    if m == 0:
        assert am == 0 and mm == 0
    else:
        assert am == (a + b) % m
        assert mm == (a * b) % m


@settings(deadline=None, max_examples=15)
@given(u256_ints, st.integers(min_value=0, max_value=M - 1))
def test_exp(a, e):
    av, ev = jnp.asarray(u256.from_int(a)), jnp.asarray(u256.from_int(e))
    assert u256.to_int(J["exp"](av, ev)) == pow(a, e, M)


@settings(deadline=None, max_examples=60)
@given(u256_ints, st.integers(min_value=0, max_value=40))
def test_byte(x, i):
    xv, iv = jnp.asarray(u256.from_int(x)), jnp.asarray(u256.from_int(i))
    got = u256.to_int(J["byte_op"](iv, xv))
    expect = (x >> (8 * (31 - i))) & 0xFF if i < 32 else 0
    assert got == expect


@settings(deadline=None, max_examples=60)
@given(u256_ints, st.integers(min_value=0, max_value=40))
def test_signextend(x, b):
    xv, bv = jnp.asarray(u256.from_int(x)), jnp.asarray(u256.from_int(b))
    got = u256.to_int(J["signextend"](bv, xv))
    if b >= 31:
        expect = x
    else:
        t = 8 * (b + 1)
        low = x % (1 << t)
        if low >= (1 << (t - 1)):
            low -= 1 << t
        expect = low % M
    assert got == expect


@settings(deadline=None, max_examples=40)
@given(u256_ints, u256_ints)
def test_bytes_roundtrip(a, b):
    av = jnp.asarray(u256.from_int(a))
    by = J["word_to_bytes"](av)
    expect = a.to_bytes(32, "big")
    assert bytes(np.asarray(by).tolist()) == expect
    assert u256.to_int(J["bytes_to_word"](by)) == a


def test_batched_vmap_matches_scalar():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 1 << 16, size=(64, 16), dtype=np.uint32)
    b = rng.integers(0, 1 << 16, size=(64, 16), dtype=np.uint32)
    av, bv = jnp.asarray(a), jnp.asarray(b)
    out = jax.jit(u256.mul)(av, bv)
    for i in range(0, 64, 7):
        assert u256.to_int(out[i]) == (u256.to_int(a[i]) * u256.to_int(b[i])) % M
