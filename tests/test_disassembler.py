"""Disassembler: roundtrip, selector recovery, metadata skipping, easm."""

from mythril_tpu.disassembler import Disassembly
from mythril_tpu.disassembler.asm import (
    assemble,
    disassemble,
    find_metadata_length,
    instruction_list_to_easm,
    push,
    safe_decode,
    to_dense,
)


def test_assemble_disassemble_roundtrip():
    src = [
        "PUSH1 0x60",
        "PUSH1 0x40",
        "MSTORE",
        "CALLDATASIZE",
        "ISZERO",
        "PUSH2 0x00ff",
        "JUMPI",
        "JUMPDEST",
        "STOP",
    ]
    code = assemble(src)
    instrs = disassemble(code)
    assert [i.opcode for i in instrs] == [s.split()[0] for s in src]
    assert instrs[0].argument == "0x60"
    assert instrs[5].argument == "0x00ff"
    assert instrs[5].address == 7


def test_truncated_push_padded():
    # PUSH2 with only one data byte at end of code
    instrs = disassemble(bytes([0x61, 0xAA]))
    assert instrs[0].opcode == "PUSH2"
    assert instrs[0].argument == "0xaa00"


def _metadata_blob() -> bytes:
    """A valid solc-style CBOR tail: content with a bzzr key plus the
    2-byte big-endian length find_metadata_length validates."""
    inner = b"\xa1\x65bzzr0X " + bytes(range(32))
    # the trailing 2-byte big-endian length counts the CBOR content
    # only (find_metadata_length adds the 2 length bytes itself)
    return inner + len(inner).to_bytes(2, "big")


def test_truncated_push_does_not_absorb_metadata():
    """A trailing PUSH whose operand runs past end-of-CODE must be
    zero-padded per EVM semantics, not mis-sized by absorbing the solc
    metadata bytes that follow — CFG recovery depends on the
    instruction boundary (regression: the operand slice was bounded by
    the raw blob, not the code region)."""
    meta = _metadata_blob()
    # code region = PUSH1; JUMPDEST-looking byte lives in metadata
    blob = bytes([0x00, 0x60]) + meta
    assert find_metadata_length(blob) == len(meta)
    instrs = disassemble(blob)
    assert [i.opcode for i in instrs] == ["STOP", "PUSH1"]
    # EVM pads the out-of-code operand with zeros; the old behavior
    # leaked meta[0] (0xa1) into the argument
    assert instrs[1].argument == "0x00"

    # a PUSH4 cut two bytes short: in-code bytes kept, tail padded
    blob = bytes([0x63, 0xDE, 0xAD]) + meta
    instrs = disassemble(blob)
    assert instrs[0].opcode == "PUSH4"
    assert instrs[0].argument == "0xdead0000"

    # instruction boundaries must agree with the dense sweep: both
    # views see the same 3-byte code region, metadata excluded — even
    # when the first metadata byte (0xa1) would decode as an opcode
    blob = bytes([0x63, 0xDE, 0xAD]) + meta
    ops, jd = to_dense(blob)
    assert len(ops) == 3
    assert sum(len(i.argument[2:]) // 2 + 1 for i in disassemble(blob)) == 5
    # (PUSH4 reports its full padded width; the CODE region is 3 bytes
    # and to_dense stops exactly there)


def test_dense_arrays_jumpdest_mask():
    code = assemble(["PUSH1 0x5b", "JUMPDEST", "PUSH2 0x5b5b", "JUMPDEST", "STOP"])
    ops, jd = to_dense(code)
    # 0x5b byte inside push data must NOT be a valid dest
    assert jd[2] and jd[6]
    assert not jd[1] and not jd[4] and not jd[5]
    assert ops[2] == 0x5B


def test_metadata_stripped():
    code = assemble(["PUSH1 0x00", "STOP"])
    meta = b"\xa1\x65bzzr0" + bytes(34)
    blob = code + meta + len(meta).to_bytes(2, "big")
    assert find_metadata_length(blob) == len(meta) + 2
    assert [i.opcode for i in disassemble(blob)] == ["PUSH1", "STOP"]


def test_dispatcher_function_recovery():
    # minimal solidity-style dispatcher:
    #   CALLDATALOAD >> 224 == 0xa9059cbb ? jump 0x40 : fallthrough
    src = [
        "PUSH1 0x00",
        "CALLDATALOAD",
        "PUSH1 0xe0",
        "SHR",
        "DUP1",
        "PUSH4 0xa9059cbb",
        "EQ",
        "PUSH1 0x40",
        "JUMPI",
        "DUP1",
        "PUSH4 0x23b872dd",
        "EQ",
        "PUSH1 0x60",
        "JUMPI",
        "STOP",
    ]
    dis = Disassembly(assemble(src).hex())
    assert "0xa9059cbb" in dis.func_hashes
    assert "0x23b872dd" in dis.func_hashes
    addrs = dis.address_to_function_name
    assert 0x40 in addrs and 0x60 in addrs


def test_easm_and_hex_input():
    dis = Disassembly("0x6001600201")
    easm = dis.get_easm()
    assert "PUSH1 0x01" in easm and "ADD" in easm
    assert safe_decode("0x6001") == b"\x60\x01"


def test_code_hash_is_keccak():
    from mythril_tpu.support.keccak import keccak256

    dis = Disassembly("0x6001")
    assert dis.code_hash == "0x" + keccak256(b"\x60\x01").hex()


def test_push_helper():
    assert push(0x60) == "PUSH1 0x60"
    assert push(0xA9059CBB) == "PUSH4 0xa9059cbb"
