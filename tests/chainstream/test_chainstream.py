"""Reorg-safe chain-head streaming suite (mythril_tpu/chainstream).

Everything here runs against a SCRIPTED in-process fake chain — no
network, no subprocess (the per-test idiom the reference repo uses
for "test chain interaction without a chain"). The fake exposes the
exact `EthJsonRpc` method surface the pool calls, so the real
`RpcEndpoint`/`RpcPool` machinery (breakers, retry ladders, quorum)
runs unmodified; only the wire is fake. The subprocess SIGKILL
harness with real HTTP endpoints is tools/chainstream_smoke.py
([testenv:chainstream]).

Covered: head advance + deployment/proxy-upgrade extraction, static
line-rate triage split, the 3-block reorg walk (rollback + alert
retraction + canonical re-ingest dedupe), bounded gap backfill,
endpoint death -> breaker -> failover, all-endpoints-down redline,
quorum head arithmetic, cursor journal crash replay (torn tail,
rollback re-truncation, compaction), alert log recovery, fleet
survivor submission with content-derived idempotency keys +
deadline-aware shedding + terminal supersede, and the hardened
client's URL/typed-exception surface.
"""

import hashlib
import json
import os
import threading

import pytest

from mythril_tpu.chainstream import (
    AllEndpointsDown,
    ChainWatcher,
    CursorJournal,
    RpcEndpoint,
    RpcPool,
    StaticTriage,
    WatchConfig,
    alert_id_for,
    idempotency_key_for,
    replay_dir,
)
from mythril_tpu.chainstream.alerts import (
    STATUS_FIRED,
    STATUS_RETRACTED,
    STATUS_SUPERSEDED,
    AlertSink,
)
from mythril_tpu.ethereum.interface.rpc.client import EthJsonRpc
from mythril_tpu.ethereum.interface.rpc.exceptions import (
    ConnectionError as RpcConnectionError,
)
from mythril_tpu.ethereum.interface.rpc.exceptions import (
    RpcErrorResponse,
    RpcTransportError,
)

pytestmark = pytest.mark.chainstream

#: CALLER SELFDESTRUCT — module-applicable, always a survivor
KILLABLE = "33ff"
#: ORIGIN SELFDESTRUCT — a second distinct survivor shape
KILLABLE2 = "32ff"
#: STOP — the semantic screen proves no module fires: settled static
INERT = "00"


def _sha(text: str) -> str:
    return "0x" + hashlib.sha256(text.encode()).hexdigest()


def _addr(seed: str) -> str:
    return "0x" + hashlib.sha256(seed.encode()).hexdigest()[:40]


class FakeChain:
    """A scripted canonical chain + code/receipt stores."""

    def __init__(self):
        self.blocks = []
        self.codes = {}
        self.receipts = {}

    def head(self) -> int:
        return len(self.blocks) - 1

    def add_block(self, deployments=(), upgrades=(), salt="main"):
        """Append one block. `deployments` = [(address, code_hex)],
        `upgrades` = [(proxy, impl_address, impl_code_hex)]."""
        number = len(self.blocks)
        parent = (
            self.blocks[-1]["hash"] if self.blocks else "0x" + "0" * 64
        )
        txs = []
        for i, (address, code_hex) in enumerate(deployments):
            txh = _sha(f"tx:{number}:{i}:{salt}")
            txs.append({"hash": txh, "to": None, "input": "0x"})
            self.receipts[txh] = {
                "transactionHash": txh,
                "contractAddress": address,
            }
            self.codes[address.lower()] = "0x" + code_hex
        for i, (proxy, impl, code_hex) in enumerate(upgrades):
            txh = _sha(f"up:{number}:{i}:{salt}")
            word = impl[2:].rjust(64, "0")
            txs.append({
                "hash": txh,
                "to": proxy,
                "input": "0x3659cfe6" + word,
            })
            self.codes[impl.lower()] = "0x" + code_hex
        block = {
            "number": hex(number),
            "hash": _sha(f"block:{number}:{salt}"),
            "parentHash": parent,
            "transactions": txs,
        }
        self.blocks.append(block)
        return block

    def reorg(self, depth: int, salt: str):
        """Orphan the last `depth` blocks and regrow them (different
        hashes, different salt) — the competing fork won."""
        orphaned = self.blocks[-depth:]
        self.blocks = self.blocks[:-depth]
        for _ in range(depth):
            self.add_block(salt=salt)
        return orphaned


class FakeRpcClient:
    """The EthJsonRpc method surface over a FakeChain; `down` makes
    every call a transport failure (the endpoint died)."""

    def __init__(self, chain: FakeChain, lag: int = 0):
        self.chain = chain
        self.down = False
        self.lag = lag  # blocks behind the scripted head
        self.calls = 0

    def _gate(self):
        self.calls += 1
        if self.down:
            raise RpcConnectionError("endpoint down")

    def eth_blockNumber(self, timeout_s=None):
        self._gate()
        return max(0, self.chain.head() - self.lag)

    def eth_getBlockByNumber(self, block, tx_objects=True, timeout_s=None):
        self._gate()
        number = block if isinstance(block, int) else int(block, 16)
        if 0 <= number <= self.chain.head() - self.lag:
            return self.chain.blocks[number]
        raise RpcErrorResponse(-32001, f"unknown block {number}")

    def eth_getTransactionReceipt(self, tx_hash, timeout_s=None):
        self._gate()
        receipt = self.chain.receipts.get(tx_hash)
        if receipt is None:
            raise RpcErrorResponse(-32001, "unknown transaction")
        return receipt

    def eth_getCode(self, address, default_block="latest", timeout_s=None):
        self._gate()
        return self.chain.codes.get(address.lower(), "0x")


class FakeFront:
    """ServiceClient-shaped sink for survivor submissions."""

    def __init__(self, fail=False):
        self.fail = fail
        self.submissions = []
        self.jobs = {}

    def submit_ex(self, code_hex, max_waves=None, deadline_s=None,
                  host_walk=None, lanes=None, idempotency_key=None,
                  frontier=None):
        if self.fail:
            raise OSError("front unreachable")
        self.submissions.append(
            {"code": code_hex, "idempotency_key": idempotency_key}
        )
        deduped = any(
            s["idempotency_key"] == idempotency_key
            for s in self.submissions[:-1]
        )
        job_id = f"job-{idempotency_key}"
        self.jobs.setdefault(
            job_id, {"job_id": job_id, "state": "queued", "issues": []}
        )
        return {"job_id": job_id, "state": "queued", "deduped": deduped}

    def job(self, job_id):
        return self.jobs[job_id]

    def settle(self, job_id, issues):
        self.jobs[job_id].update(state="done", issues=issues)


def make_pool(chain, n=1, quorum=1, **endpoint_kw):
    clients = [FakeRpcClient(chain) for _ in range(n)]
    kw = dict(retries=0, failure_threshold=2, recovery_s=60.0)
    kw.update(endpoint_kw)
    endpoints = [
        RpcEndpoint(f"e{i}", client, **kw)
        for i, client in enumerate(clients)
    ]
    return RpcPool(endpoints, quorum=quorum), clients


def make_watcher(chain, tmp_path, front=None, n=1, **cfg_kw):
    pool, clients = make_pool(chain, n=n)
    kw = dict(start_block=0, fsync=False, poll_interval_s=0.0)
    kw.update(cfg_kw)
    watcher = ChainWatcher(
        pool, str(tmp_path / "state"), front=front,
        config=WatchConfig(**kw),
    )
    return watcher, clients


# ---------------------------------------------------------------------------
# advance + extraction + triage
# ---------------------------------------------------------------------------
def test_watcher_follows_head_and_fires_on_deployments(tmp_path):
    chain = FakeChain()
    chain.add_block()
    killer = _addr("killer")
    chain.add_block(deployments=[(killer, KILLABLE)])
    chain.add_block(deployments=[(_addr("inert"), INERT)])
    watcher, _ = make_watcher(chain, tmp_path)
    facts = watcher.tick()
    assert facts["head"] == 2
    assert facts["ingested"] == 3
    assert watcher.cursor.tip().number == 2
    fired = watcher.alerts.alerts(STATUS_FIRED)
    assert len(fired) == 2  # both deployments alert; triage differs
    by_addr = {a.address: a for a in fired}
    assert "AccidentallyKillable" in by_addr[killer].findings
    assert watcher.triage.stats()["survivors"] == 1
    assert watcher.triage.stats()["settled_static"] == 1


def test_proxy_upgrade_extraction_alerts_on_implementation(tmp_path):
    chain = FakeChain()
    chain.add_block()
    impl = _addr("impl")
    chain.add_block(upgrades=[(_addr("proxy"), impl, KILLABLE)])
    watcher, _ = make_watcher(chain, tmp_path)
    watcher.tick()
    fired = watcher.alerts.alerts(STATUS_FIRED)
    assert len(fired) == 1
    assert fired[0].address == impl
    assert fired[0].kind == "proxy-upgrade"


def test_cursor_advances_before_results_surface(tmp_path, monkeypatch):
    """The at-least-once contract: the fsync'd advance precedes the
    block's alerts, so a crash between them redelivers (never loses)
    the tip."""
    chain = FakeChain()
    chain.add_block(deployments=[(_addr("k"), KILLABLE)])
    watcher, _ = make_watcher(chain, tmp_path)
    order = []
    original_advance = watcher.cursor.advance
    original_fire = watcher.alerts.fire

    def spy_advance(*a, **k):
        order.append("advance")
        return original_advance(*a, **k)

    def spy_fire(*a, **k):
        order.append("fire")
        return original_fire(*a, **k)

    monkeypatch.setattr(watcher.cursor, "advance", spy_advance)
    monkeypatch.setattr(watcher.alerts, "fire", spy_fire)
    watcher.tick()
    assert order == ["advance", "fire"]


# ---------------------------------------------------------------------------
# reorg
# ---------------------------------------------------------------------------
def test_three_block_reorg_rolls_back_and_retracts(tmp_path):
    chain = FakeChain()
    chain.add_block()
    orphan_addr = _addr("orphan-deploy")
    chain.add_block(deployments=[(orphan_addr, KILLABLE)])
    chain.add_block()
    chain.add_block()
    watcher, _ = make_watcher(chain, tmp_path)
    watcher.tick()
    assert watcher.cursor.tip().number == 3
    assert len(watcher.alerts.alerts(STATUS_FIRED)) == 1

    chain.reorg(3, salt="fork")  # blocks 1..3 regrow without the deploy
    watcher.tick()
    assert watcher.reorgs == 1
    assert watcher.deepest_reorg == 3
    assert watcher.cursor.tip().number == 3
    assert watcher.cursor.tip().block_hash == chain.blocks[3]["hash"]
    retracted = watcher.alerts.alerts(STATUS_RETRACTED)
    assert [a.address for a in retracted] == [orphan_addr]
    # the rollback is durably journaled
    facts = replay_dir(str(tmp_path / "state" / "cursor"))
    assert facts["rollbacks"] == 1


def test_reorg_reingest_dedupes_unchanged_contract(tmp_path):
    """A deployment on BOTH sides of the fork keeps one alert id on
    each side's block hash — the orphaned one retracts, the canonical
    one stands — and the fleet sees ONE job (content-derived key)."""
    chain = FakeChain()
    chain.add_block()
    addr = _addr("both-sides")
    chain.add_block(deployments=[(addr, KILLABLE)])
    front = FakeFront()
    watcher, _ = make_watcher(chain, tmp_path, front=front)
    watcher.tick()
    # fork: same deployment lands in the replacement block too
    chain.blocks = chain.blocks[:-1]
    chain.add_block(deployments=[(addr, KILLABLE)], salt="fork")
    chain.add_block(salt="fork")
    watcher.tick()
    fired = watcher.alerts.alerts(STATUS_FIRED)
    retracted = watcher.alerts.alerts(STATUS_RETRACTED)
    assert len(fired) == 1 and len(retracted) == 1
    assert fired[0].address == addr
    keys = {s["idempotency_key"] for s in front.submissions}
    assert keys == {idempotency_key_for(fired[0].code_hash)}
    assert watcher.submitted == 1
    assert watcher.deduped == 1  # the re-ingest deduped at the front


# ---------------------------------------------------------------------------
# gap backfill
# ---------------------------------------------------------------------------
def test_gap_backfill_is_bounded_per_tick_and_complete(tmp_path):
    chain = FakeChain()
    chain.add_block()
    watcher, _ = make_watcher(chain, tmp_path, backfill_batch=4)
    watcher.tick()
    deployed = []
    for i in range(10):
        addr = _addr(f"gap:{i}")
        chain.add_block(deployments=[(addr, KILLABLE)])
        deployed.append(addr)
    facts = watcher.tick()
    assert facts["ingested"] == 4  # bounded: one batch per tick
    assert watcher.head_lag() == 6
    while watcher.head_lag():
        watcher.tick()
    fired = {a.address for a in watcher.alerts.alerts(STATUS_FIRED)}
    assert fired == set(deployed)  # zero missed deployments


# ---------------------------------------------------------------------------
# endpoint death / failover / quorum
# ---------------------------------------------------------------------------
def test_endpoint_death_fails_over_and_stream_continues(tmp_path):
    chain = FakeChain()
    chain.add_block()
    watcher, clients = make_watcher(chain, tmp_path, n=2)
    watcher.tick()
    clients[0].down = True
    chain.add_block(deployments=[(_addr("after-death"), KILLABLE)])
    watcher.tick()
    watcher.tick()  # second failed poll trips the threshold-2 breaker
    assert watcher.cursor.tip().number == 1
    assert watcher.pool.up_count() == 1
    assert watcher.pool.open_reasons() == ["breaker-open:rpc:e0"]
    assert len(watcher.alerts.alerts(STATUS_FIRED)) == 1


def test_all_endpoints_down_redlines_without_stalling(tmp_path):
    chain = FakeChain()
    chain.add_block()
    watcher, clients = make_watcher(chain, tmp_path, n=2)
    watcher.tick()
    for client in clients:
        client.down = True
    for _ in range(3):
        watcher.tick()  # never raises; the cursor just holds
    assert watcher.pool.up_count() == 0
    reasons = watcher._saturation_reasons()
    assert "rpc-endpoints-down" in reasons
    assert "breaker-open:rpc:e0" in reasons
    clients[0].down = False
    chain.add_block()
    # breakers are in OPEN with recovery_s=60; force the half-open
    # probe by advancing the breaker clock through its stats surface
    watcher.pool.endpoints[0].breaker._opened_t = -1e9
    watcher.tick()
    assert watcher.cursor.tip().number == 1


def test_transport_errors_feed_breaker_but_rpc_errors_do_not():
    chain = FakeChain()
    chain.add_block()
    client = FakeRpcClient(chain)
    endpoint = RpcEndpoint(
        "e0", client, retries=0, failure_threshold=2, recovery_s=60.0
    )
    for _ in range(5):
        with pytest.raises(RpcErrorResponse):
            endpoint.call("eth_getBlockByNumber", 99, True)
    assert endpoint.alive  # in-band errors are not death
    client.down = True
    for _ in range(2):
        with pytest.raises(RpcTransportError):
            endpoint.call("eth_blockNumber")
    assert not endpoint.alive


def test_quorum_head_is_the_quorum_th_highest(tmp_path):
    chain = FakeChain()
    for _ in range(9):
        chain.add_block()
    pool, clients = make_pool(chain, n=3, quorum=2)
    clients[1].lag = 3  # an endpoint behind the head
    clients[2].lag = 8  # an endpoint way behind
    assert pool.poll_heads() == 5  # 2nd-highest of (8, 5, 0)
    clients[0].down = True
    assert pool.poll_heads() == 0  # quorum clamps to the live pair


def test_all_down_pool_call_raises_allendpointsdown():
    chain = FakeChain()
    chain.add_block()
    pool, clients = make_pool(chain, n=2)
    for client in clients:
        client.down = True
    with pytest.raises(AllEndpointsDown):
        pool.call("eth_blockNumber")


# ---------------------------------------------------------------------------
# cursor journal
# ---------------------------------------------------------------------------
def test_cursor_journal_replays_chain_and_compacts(tmp_path):
    d = str(tmp_path / "cursor")
    journal = CursorJournal(d, fsync=False)
    for n in range(5):
        journal.advance(n, _sha(f"b{n}"), _sha(f"b{n-1}"))
    journal.rollback_to(2)
    journal.advance(3, _sha("b3'"), _sha("b2"))
    journal.close()  # no drain record: a crash

    recovered = CursorJournal(d, fsync=False)
    facts = recovered.recover()
    assert facts["clean_shutdown"] is False
    assert facts["rollbacks"] == 1
    assert recovered.tip().number == 3
    assert recovered.tip().block_hash == _sha("b3'")
    assert [e.number for e in recovered.chain()] == [0, 1, 2, 3]
    assert facts["compacted_segments"] == 1
    recovered.mark_drain()
    recovered.close()
    third = CursorJournal(d, fsync=False)
    assert third.recover()["clean_shutdown"] is True


def test_cursor_journal_tolerates_torn_tail(tmp_path):
    d = str(tmp_path / "cursor")
    journal = CursorJournal(d, fsync=False)
    journal.advance(0, _sha("b0"))
    journal.advance(1, _sha("b1"), _sha("b0"))
    journal.close()
    with open(journal.path, "a") as fp:
        fp.write('{"event": "advance", "number": 2, "ha')  # torn write
    recovered = CursorJournal(d, fsync=False)
    facts = recovered.recover()
    assert facts["torn_lines"] == 1
    assert recovered.tip().number == 1


def test_cursor_journal_refuses_newer_schema(tmp_path):
    d = str(tmp_path / "cursor")
    journal = CursorJournal(d, fsync=False)
    journal.advance(0, _sha("b0"))
    journal.close()
    with open(journal.path, "a") as fp:
        fp.write(json.dumps({
            "event": "advance", "number": 1, "hash": _sha("b1"),
            "schema": 99,
        }) + "\n")
    facts = CursorJournal(d, fsync=False).recover()
    assert facts["torn_lines"] == 1
    assert facts["tip"]["number"] == 0


# ---------------------------------------------------------------------------
# alert sink
# ---------------------------------------------------------------------------
def test_alert_sink_lifecycle_and_recovery(tmp_path):
    path = str(tmp_path / "alerts.jsonl")
    sink = AlertSink(path, fsync=False)
    a = sink.fire("ch1", "0xaa", 7, "0xb7", "deployment", ["Mod"],
                  latency_s=0.2)
    again = sink.fire("ch1", "0xaa", 7, "0xb7", "deployment", ["Mod"])
    assert again.id == a.id and sink.deduped == 1
    b = sink.fire("ch2", "0xbb", 8, "0xb8", "deployment", [])
    sink.supersede(a.id, ["DeepMod"], source="fleet")
    sink.retract_blocks(["0xb8"])
    assert sink.get(a.id).status == STATUS_SUPERSEDED
    assert sink.get(b.id).status == STATUS_RETRACTED
    # a late fleet verdict cannot resurrect a retracted alert
    assert sink.supersede(b.id, ["x"]) is None
    sink.close()

    recovered = AlertSink(path, fsync=False)
    assert recovered.recover() == 2
    assert recovered.get(a.id).status == STATUS_SUPERSEDED
    assert recovered.get(a.id).findings == ["DeepMod"]
    assert recovered.get(b.id).status == STATUS_RETRACTED
    # recovery + redelivery: the same content dedupes, no double fire
    third = recovered.fire("ch1", "0xaa", 7, "0xb7", "deployment", ["Mod"])
    assert third.id == a.id and recovered.deduped == 1
    recovered.close()


def test_alert_ids_are_content_derived():
    assert alert_id_for("c", "b") == alert_id_for("c", "b")
    assert alert_id_for("c", "b1") != alert_id_for("c", "b2")


# ---------------------------------------------------------------------------
# triage
# ---------------------------------------------------------------------------
def test_triage_split_and_idempotency_keys():
    triage = StaticTriage()
    survivor = triage.triage(bytes.fromhex(KILLABLE))
    settled = triage.triage(bytes.fromhex(INERT))
    assert survivor.survivor and not settled.survivor
    assert "AccidentallyKillable" in survivor.findings
    assert survivor.idempotency_key == (
        "chainstream:" + hashlib.sha256(bytes.fromhex(KILLABLE)).hexdigest()
    )
    # the verdict memo makes re-ingest free
    assert triage.triage(bytes.fromhex(KILLABLE)) is survivor
    assert triage.stats()["triaged"] == 2


# ---------------------------------------------------------------------------
# fleet handoff
# ---------------------------------------------------------------------------
def test_survivors_submit_under_content_keys_and_supersede(tmp_path):
    chain = FakeChain()
    chain.add_block()
    chain.add_block(deployments=[
        (_addr("s1"), KILLABLE),
        (_addr("s2"), KILLABLE2),
        (_addr("s3"), INERT),  # settled static: never reaches the front
    ])
    front = FakeFront()
    watcher, _ = make_watcher(chain, tmp_path, front=front)
    watcher.tick()
    assert len(front.submissions) == 2
    for s in front.submissions:
        assert s["idempotency_key"].startswith("chainstream:")
    # the fleet settles one job; the next tick supersedes its alert
    job_id = f"job-{idempotency_key_for(hashlib.sha256(bytes.fromhex(KILLABLE)).hexdigest())}"
    front.settle(job_id, [{"title": "Unprotected Selfdestruct"}])
    chain.add_block()
    watcher.tick()
    superseded = watcher.alerts.alerts(STATUS_SUPERSEDED)
    assert len(superseded) == 1
    assert superseded[0].findings == ["Unprotected Selfdestruct"]
    assert superseded[0].source == "fleet"


def test_dead_front_sheds_to_static_only_and_never_stalls(tmp_path):
    chain = FakeChain()
    chain.add_block()
    chain.add_block(deployments=[(_addr("shed"), KILLABLE)])
    front = FakeFront(fail=True)
    watcher, _ = make_watcher(chain, tmp_path, front=front)
    watcher.tick()
    assert watcher.shed == 1
    assert watcher.cursor.tip().number == 1  # the cursor never waited
    fired = watcher.alerts.alerts(STATUS_FIRED)
    assert len(fired) == 1 and fired[0].source == "static"


# ---------------------------------------------------------------------------
# crash recovery end to end
# ---------------------------------------------------------------------------
def test_recover_redelivers_tip_and_dedupes(tmp_path):
    chain = FakeChain()
    chain.add_block()
    chain.add_block(deployments=[(_addr("redeliver"), KILLABLE)])
    watcher, _ = make_watcher(chain, tmp_path)
    watcher.tick()
    assert len(watcher.alerts.alerts()) == 1
    # crash: no drain record, no clean close
    watcher.cursor._fp.close()
    watcher.alerts._fp.close()

    revived, _ = make_watcher(chain, tmp_path)
    facts = revived.recover()
    assert facts["clean_shutdown"] is False
    assert facts["redelivered"] is True
    assert facts["alerts_indexed"] == 1
    # at-least-once + content-derived ids: redelivery deduped
    assert revived.alerts.deduped == 1
    assert len(revived.alerts.alerts(STATUS_FIRED)) == 1
    assert revived.cursor.tip().number == 1
    chain.add_block(deployments=[(_addr("post-crash"), KILLABLE2)])
    revived.tick()
    assert revived.cursor.tip().number == 2
    assert len(revived.alerts.alerts(STATUS_FIRED)) == 2


def test_recover_after_clean_drain_does_not_redeliver(tmp_path):
    chain = FakeChain()
    chain.add_block(deployments=[(_addr("clean"), KILLABLE)])
    watcher, _ = make_watcher(chain, tmp_path)
    watcher.tick()
    watcher.close()  # drain record written
    revived, _ = make_watcher(chain, tmp_path)
    facts = revived.recover()
    assert facts["clean_shutdown"] is True
    assert facts["redelivered"] is False
    assert revived.alerts.deduped == 0


# ---------------------------------------------------------------------------
# hardened client surface
# ---------------------------------------------------------------------------
def test_from_url_roundtrip():
    for url in (
        "http://127.0.0.1:8545",
        "https://rpc.example.org",
        "http://node.example.org:8545/rpc/v1",
    ):
        assert EthJsonRpc.from_url(url).url == url


def test_watcher_health_payload_carries_chainstream_objectives(tmp_path):
    chain = FakeChain()
    chain.add_block()
    watcher, _ = make_watcher(chain, tmp_path)
    watcher.tick()
    payload = watcher.health.healthz_payload()
    names = {o["objective"] for o in payload["objectives"]}
    assert names == {"alert-latency-p50", "survivor-shed-share"}


def test_concurrent_fires_are_single_threaded_safe(tmp_path):
    """The sink is called from the tick thread only in production,
    but the lock discipline must hold under concurrent fire anyway
    (the supersede poll may race a fire in future refactors)."""
    sink = AlertSink(str(tmp_path / "alerts.jsonl"), fsync=False)
    errors = []

    def fire(i):
        try:
            sink.fire(f"ch{i % 4}", f"0x{i}", i, f"0xb{i % 4}",
                      "deployment", [])
        except Exception as why:  # pragma: no cover
            errors.append(why)

    threads = [
        threading.Thread(target=fire, args=(i,)) for i in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(sink.alerts()) == 4  # 4 distinct (code, block) pairs
    sink.close()
