"""Facade tests with mocked chain access (reference test strategy:
tests/mythril/* using mock/pytest_mock)."""

import json
from unittest import mock

import pytest

from mythril_tpu.exceptions import CriticalError
from mythril_tpu.mythril import MythrilAnalyzer, MythrilConfig, MythrilDisassembler


class FakeEth:
    """In-memory RPC double."""

    def __init__(self, code="0x33ff", storage=None, balance=7):
        self._code = code
        self._storage = storage or {}
        self._balance = balance

    def eth_getCode(self, address, default_block="latest"):
        return self._code

    def eth_getStorageAt(self, address, position=0, block="latest"):
        return "0x" + format(self._storage.get(position, 0), "064x")

    def eth_getBalance(self, address, default_block="latest"):
        return self._balance


def test_load_from_bytecode_runtime():
    disassembler = MythrilDisassembler(eth=None)
    address, contract = disassembler.load_from_bytecode("33ff", bin_runtime=True)
    assert contract.code == "33ff"
    assert contract.name == "MAIN"
    assert "SUICIDE" in contract.get_easm()


def test_load_from_address():
    disassembler = MythrilDisassembler(eth=FakeEth(code="0x6001600055"))
    address, contract = disassembler.load_from_address(
        "0x" + "11" * 20
    )
    assert contract.code == "0x6001600055"


def test_load_from_address_empty_code_raises():
    disassembler = MythrilDisassembler(eth=FakeEth(code="0x"))
    with pytest.raises(CriticalError):
        disassembler.load_from_address("0x" + "11" * 20)


def test_load_from_address_invalid_format_raises():
    disassembler = MythrilDisassembler(eth=None)
    with pytest.raises(CriticalError):
        disassembler.load_from_address("nonsense")


def test_read_storage_plain_slots():
    disassembler = MythrilDisassembler(eth=FakeEth(storage={0: 5, 1: 6}))
    out = disassembler.get_state_variable_from_storage("0x" + "11" * 20, ["0", "2"])
    lines = out.splitlines()
    assert len(lines) == 2
    assert lines[0].endswith(format(5, "064x"))


def test_read_storage_mapping():
    disassembler = MythrilDisassembler(eth=FakeEth())
    out = disassembler.get_state_variable_from_storage(
        "0x" + "11" * 20, ["mapping", "2", "somekey"]
    )
    assert out  # keccak-derived slot resolved and queried


def test_hash_for_function_signature():
    assert (
        MythrilDisassembler.hash_for_function_signature("transfer(address,uint256)")
        == "0xa9059cbb"
    )


def test_config_creates_ini(tmp_path, monkeypatch):
    monkeypatch.setenv("MYTHRIL_DIR", str(tmp_path))
    config = MythrilConfig()
    assert (tmp_path / "config.ini").exists()
    content = (tmp_path / "config.ini").read_text()
    assert "dynamic_loading" in content


def test_config_rpc_settings(tmp_path, monkeypatch):
    monkeypatch.setenv("MYTHRIL_DIR", str(tmp_path))
    config = MythrilConfig()
    config.set_api_rpc("localhost:7777")
    assert config.eth.host == "localhost"
    assert config.eth.port == 7777
    with pytest.raises(CriticalError):
        config.set_api_rpc("not-a-valid-spec-at-all")


def test_analyzer_end_to_end_with_mocked_chain():
    disassembler = MythrilDisassembler(eth=None)
    disassembler.load_from_bytecode("33ff", bin_runtime=True)
    analyzer = MythrilAnalyzer(
        disassembler,
        strategy="bfs",
        use_onchain_data=False,
        address="0x" + "11" * 20,
        execution_timeout=60,
        create_timeout=10,
        max_depth=64,
        loop_bound=3,
    )
    report = analyzer.fire_lasers(transaction_count=1)
    data = json.loads(report.as_json())
    assert data["success"] is True
    assert any(i["swc-id"] == "106" for i in data["issues"])


def test_analyzer_multi_contract_overlapped_prepass():
    """With several contracts and --device-prepass always, fire_lasers
    runs the overlapped striped prepass beside the per-contract loop
    (the reference's sequential for-loop becomes the host half of a
    host+device pipeline) and still reports every contract's issues."""
    from mythril_tpu.support.support_args import args

    disassembler = MythrilDisassembler(eth=None)
    disassembler.load_from_bytecode("33ff", bin_runtime=True)  # SWC-106
    disassembler.load_from_bytecode(
        "600035600757005bfe", bin_runtime=True  # SWC-110
    )
    analyzer = MythrilAnalyzer(
        disassembler,
        strategy="bfs",
        use_onchain_data=False,
        address="0x" + "11" * 20,
        execution_timeout=60,
        create_timeout=10,
        max_depth=64,
        loop_bound=3,
    )
    saved = (args.device_prepass, args.device_solving)
    args.device_prepass = "always"  # engage the overlap on the CPU mesh
    try:
        report = analyzer.fire_lasers(transaction_count=1)
    finally:
        args.device_prepass, args.device_solving = saved
    data = json.loads(report.as_json())
    assert data["success"] is True
    swcs = {i["swc-id"] for i in data["issues"]}
    assert "106" in swcs
    assert "110" in swcs
