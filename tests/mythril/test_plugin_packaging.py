"""Installed-package plugin discovery smoke test (L10 reachability).

The reference's extension system only works through setuptools entry
points in installed package metadata (/root/reference/setup.py
entry_points `mythril.plugins`; mythril/plugin/discovery.py loads the
group). This harness proves the same path end-to-end WITHOUT a pip
install: it fabricates a real `.dist-info` on sys.path carrying the
exact entry point pyproject.toml declares, then drives
PluginDiscovery -> build_plugin -> MythrilPluginLoader.load and checks
the example plugin lands in the laser plugin registry.
"""

import sys
import textwrap

import pytest

from mythril_tpu.plugin.discovery import ENTRY_POINT_GROUP, PluginDiscovery


@pytest.fixture()
def installed_example_plugin(tmp_path, monkeypatch):
    dist = tmp_path / "mythril_tpu_example-1.0.0.dist-info"
    dist.mkdir()
    (dist / "METADATA").write_text(
        "Metadata-Version: 2.1\n"
        "Name: mythril-tpu-example\n"
        "Version: 1.0.0\n"
    )
    (dist / "entry_points.txt").write_text(
        textwrap.dedent(
            f"""\
            [{ENTRY_POINT_GROUP}]
            coverage-metrics = mythril_tpu.plugin.examples:CoverageMetricsPlugin
            """
        )
    )
    monkeypatch.syspath_prepend(str(tmp_path))
    # Reset on the singleton INSTANCE: the CLI import path populates
    # the cache as an instance attribute, which would shadow a reset of
    # the class attribute and skip the re-scan entirely. The teardown
    # reset keeps the fabricated entry point from leaking into later
    # tests.
    PluginDiscovery()._installed_plugins = None
    yield
    PluginDiscovery()._installed_plugins = None


def test_discovery_finds_entry_point(installed_example_plugin):
    discovery = PluginDiscovery()
    assert discovery.is_installed("coverage-metrics")
    assert "coverage-metrics" in discovery.get_plugins()
    # not default-enabled: must not appear in the auto-load set
    assert "coverage-metrics" not in discovery.get_plugins(default_enabled=True)


def test_discovered_plugin_builds_and_loads(installed_example_plugin):
    from mythril_tpu.laser.plugin.loader import LaserPluginLoader
    from mythril_tpu.plugin.interface import MythrilLaserPlugin
    from mythril_tpu.plugin.loader import MythrilPluginLoader

    plugin = PluginDiscovery().build_plugin("coverage-metrics", {})
    assert isinstance(plugin, MythrilLaserPlugin)

    loader = MythrilPluginLoader()
    before = list(loader.loaded_plugins)
    try:
        loader.load(plugin)
        assert plugin in loader.loaded_plugins
        assert (
            LaserPluginLoader().laser_plugin_builders["coverage-metrics"]
            is plugin
        )
        # the builder must be instrumentable: is_enabled reads
        # builder.enabled, which MythrilPlugin.__init__ does not set
        assert LaserPluginLoader().is_enabled("coverage-metrics")
    finally:
        loader.loaded_plugins[:] = before
        LaserPluginLoader().laser_plugin_builders.pop("coverage-metrics", None)


def test_pyproject_declares_the_same_entry_point():
    """The fabricated metadata above must stay in lockstep with what a
    real `pip install` would register."""
    from pathlib import Path

    text = (Path(__file__).parents[2] / "pyproject.toml").read_text()
    assert '[project.entry-points."mythril.plugins"]' in text
    assert (
        'coverage-metrics = "mythril_tpu.plugin.examples:CoverageMetricsPlugin"'
        in text
    )
    assert 'myth = "mythril_tpu.interfaces.cli:main"' in text


def test_example_plugin_instruments_a_vm():
    """The built plugin's hooks actually fire on a real (tiny) run."""
    from mythril_tpu.plugin.examples import CoverageMetricsPlugin

    builder = CoverageMetricsPlugin()
    inner = builder()

    class _Bus:
        def __init__(self):
            self.hooks = {}

        def laser_hook(self, name):
            def deco(fn):
                self.hooks[name] = fn

            return deco

    bus = _Bus()
    inner.initialize(bus)
    assert set(bus.hooks) == {"execute_state", "stop_sym_exec"}

    class _State:
        mstate = type("M", (), {"pc": 3})()

        def get_current_instruction(self):
            return {"opcode": "JUMPDEST", "address": 3}

    bus.hooks["execute_state"](_State())
    bus.hooks["execute_state"](_State())
    bus.hooks["stop_sym_exec"]()
    assert inner.instructions == 2
    assert inner.jumpdests == {3}
