"""The tier-ladder SLO engine + health state machine (observe/slo.py,
tier-1 `observe` marker).

Pins: burn-rate arithmetic for ratio and latency objectives over a
synthetic registry with a fake clock, the multi-window flap damper,
the ok -> degraded -> redlined -> ok transitions, the enumerated
readiness reasons, and the mtpu_health_* gauge exports. CPU-only,
no service, sub-second."""

from __future__ import annotations

import pytest

from mythril_tpu.observe.registry import (
    LATENCY_BUCKETS,
    MetricsRegistry,
)
from mythril_tpu.observe.slo import (
    NOT_READY_DRAINING,
    NOT_READY_KERNEL_WARMUP,
    NOT_READY_WARMING,
    STATE_DEGRADED,
    STATE_OK,
    STATE_REDLINED,
    HealthMonitor,
    Objective,
    SloEngine,
    quantile_from_buckets,
)

pytestmark = pytest.mark.observe


class FakeClock:
    def __init__(self) -> None:
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


def ratio_engine(reg, clock, budget=0.1, **kw):
    objective = Objective(
        name="avail",
        kind="ratio",
        budget=budget,
        numerator=("bad_total", {"outcome": "bad"}),
        denominator=("all_total", {}),
    )
    return objective, SloEngine(
        [objective], short_window_s=10.0, long_window_s=60.0,
        redline_burn=10.0, reg=reg, clock=clock, **kw
    )


def test_ratio_objective_burn_and_states():
    reg = MetricsRegistry()
    clock = FakeClock()
    _obj, engine = ratio_engine(reg, clock, budget=0.1)
    bad = reg.counter("bad_total").labels(outcome="bad")
    total = reg.counter("all_total")

    # the FIRST sample has no window: zero burn regardless of what
    # the registry accumulated before this engine existed
    bad.inc(3)
    total.inc(3)
    (status,) = engine.sample()
    assert status.state == STATE_OK and status.burn_short == 0.0

    # healthy traffic: 100 events, 1 bad -> fraction 0.01, burn 0.1
    total.inc(100)
    bad.inc(1)
    clock.advance(1.0)
    (status,) = engine.sample()
    assert status.state == STATE_OK
    assert status.burn_short == pytest.approx(0.1)

    # near-budget traffic: 9% bad in the short window (the earlier
    # samples age out of it) -> burn just under 1.0
    clock.advance(11.0)
    total.inc(100)
    bad.inc(9)
    (status,) = engine.sample()
    assert status.burn_short == pytest.approx(0.9, abs=0.01)

    # a bad storm: 100% bad events -> burn 10 on the short window,
    # and once the long window agrees the state redlines
    for _ in range(4):
        clock.advance(2.0)
        total.inc(50)
        bad.inc(50)
        (status,) = engine.sample()
    assert status.burn_short >= 10.0
    assert status.state in (STATE_DEGRADED, STATE_REDLINED)

    # recovery: clean traffic drains the short window first
    for _ in range(8):
        clock.advance(2.0)
        total.inc(200)
        (status,) = engine.sample()
    assert status.state == STATE_OK


def test_multi_window_damps_one_sample_spike():
    """A single bad burst inside an otherwise long clean history must
    NOT degrade: the long window has not burned."""
    reg = MetricsRegistry()
    clock = FakeClock()
    _obj, engine = ratio_engine(reg, clock, budget=0.01)
    total = reg.counter("all_total")
    bad = reg.counter("bad_total").labels(outcome="bad")
    # a minute of clean traffic fills the long window
    for _ in range(30):
        clock.advance(2.0)
        total.inc(100)
        engine.sample()
    # one hot sample: 50% bad for one tick
    clock.advance(2.0)
    total.inc(10)
    bad.inc(5)
    (status,) = engine.sample()
    assert status.burn_short > 1.0
    assert status.burn_long < 1.0  # diluted by the clean hour
    assert status.state == STATE_OK


def test_latency_objective_counts_threshold_violations():
    reg = MetricsRegistry()
    clock = FakeClock()
    objective = Objective(
        name="settle-p95",
        kind="latency",
        budget=0.05,
        metric="lat_seconds",
        threshold_s=1.0,
    )
    engine = SloEngine(
        [objective], short_window_s=10.0, long_window_s=60.0,
        reg=reg, clock=clock,
    )
    engine.sample()  # the windowless first sample primes the ring
    clock.advance(1.0)
    hist = reg.histogram("lat_seconds", buckets=LATENCY_BUCKETS)
    for _ in range(95):
        hist.observe(0.01)
    for _ in range(5):
        hist.observe(20.0)
    clock.advance(1.0)
    (status,) = engine.sample()
    # 5/100 above 1.0s at budget 0.05 -> burn exactly 1.0
    assert status.burn_short == pytest.approx(1.0)
    assert status.p95 is not None
    # now a stall: everything lands above the threshold (the clean
    # batch ages out of the short window)
    clock.advance(9.0)
    for _ in range(50):
        hist.observe(30.0)
    (status,) = engine.sample()
    assert status.burn_short == pytest.approx(20.0)  # 100% / 5%
    assert status.p95 > 1.0


def test_idle_replica_reports_zero_burn():
    """min_events: a replica with no traffic is healthy, not
    divide-by-zero degraded."""
    reg = MetricsRegistry()
    clock = FakeClock()
    _obj, engine = ratio_engine(reg, clock)
    clock.advance(5.0)
    (status,) = engine.sample()
    assert status.state == STATE_OK
    assert status.burn_short == 0.0 and status.total == 0.0


def test_quantile_interpolation():
    bounds = (1.0, 2.0, 4.0)
    counts = [10, 10, 0, 0]  # 20 observations, all <= 2.0
    assert quantile_from_buckets(bounds, counts, 0.5) == pytest.approx(1.0)
    p95 = quantile_from_buckets(bounds, counts, 0.95)
    assert 1.0 < p95 <= 2.0
    assert quantile_from_buckets(bounds, [0, 0, 0, 0], 0.95) is None


def test_health_monitor_readiness_reasons_and_gauges():
    reg = MetricsRegistry()
    clock = FakeClock()
    _obj, engine = ratio_engine(reg, clock)
    flags = {"warming": True, "compiling": False, "draining": False}
    monitor = HealthMonitor(
        slo=engine,
        warming_fn=lambda: flags["warming"],
        compiling_fn=lambda: flags["compiling"],
        draining_fn=lambda: flags["draining"],
        reg=reg,
    )
    payload = monitor.sample()
    assert payload["ok"] is True  # liveness holds while warming
    assert payload["ready"] is False
    assert payload["not_ready_reasons"] == [NOT_READY_WARMING]
    assert reg.value("mtpu_health_ready") == 0.0

    flags["warming"] = False
    flags["compiling"] = True
    payload = monitor.sample()
    assert payload["not_ready_reasons"] == [NOT_READY_KERNEL_WARMUP]

    flags["compiling"] = False
    payload = monitor.sample()
    assert payload["ready"] is True and payload["state"] == STATE_OK
    assert reg.value("mtpu_health_state") == 0.0
    assert reg.value("mtpu_health_ready") == 1.0

    flags["draining"] = True
    payload = monitor.sample()
    assert payload["ready"] is False
    assert payload["not_ready_reasons"] == [NOT_READY_DRAINING]


def test_health_monitor_redlines_on_slo_burn_and_saturation():
    reg = MetricsRegistry()
    clock = FakeClock()
    _obj, engine = ratio_engine(reg, clock, budget=0.01)
    saturated: list = []
    monitor = HealthMonitor(
        slo=engine, saturation_fn=lambda: list(saturated), reg=reg
    )
    total = reg.counter("all_total")
    bad = reg.counter("bad_total").labels(outcome="bad")
    for _ in range(6):
        clock.advance(2.0)
        total.inc(100)
        bad.inc(100)
        payload = monitor.sample()
    assert payload["state"] == STATE_REDLINED
    assert any(
        r.startswith("slo-burn:avail") for r in payload["reasons"]
    )
    assert payload["ready"] is False
    assert reg.value("mtpu_health_state") == 2.0
    # burn-rate gauges exported per objective x window
    assert reg.value(
        "mtpu_health_burn_rate", objective="avail", window="short"
    ) >= 10.0

    # saturation reasons redline independently of the SLO windows
    saturated.append("queue-saturated")
    reg2 = MetricsRegistry()
    monitor2 = HealthMonitor(
        slo=SloEngine([], reg=reg2, clock=clock),
        saturation_fn=lambda: list(saturated),
        reg=reg2,
    )
    payload = monitor2.sample()
    assert payload["state"] == STATE_REDLINED
    assert "queue-saturated" in payload["reasons"]
