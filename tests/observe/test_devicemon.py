"""Device saturation sampler (observe/devicemon.py, tier-1 `observe`
marker): mtpu_device_* gauges on the CPU backend, the arena-occupancy
source contract, kernel-cache gauges, the live wave overlap/idle
fractions, and the exposition shape. CPU-only, sub-second."""

from __future__ import annotations

import pytest

from mythril_tpu.observe.devicemon import DeviceMonitor, device_monitor
from mythril_tpu.observe.registry import MetricsRegistry

pytestmark = pytest.mark.observe


def test_sample_publishes_cpu_backend_gauges():
    """The acceptance floor: mtpu_device_* gauges exist on the CPU
    backend — host RSS and device count always, memory only where the
    backend reports it."""
    reg = MetricsRegistry()
    monitor = DeviceMonitor(reg=reg)
    sample = monitor.sample()
    assert sample["devices"] >= 1
    assert sample["host_rss_bytes"] > 0
    assert reg.value("mtpu_device_count") >= 1
    assert reg.value("mtpu_device_host_rss_bytes") > 0
    text = reg.prometheus_text()
    assert "# TYPE mtpu_device_count gauge" in text
    assert "# TYPE mtpu_device_host_rss_bytes gauge" in text
    assert monitor.latest() == sample


def test_arena_source_occupancy_gauges():
    reg = MetricsRegistry()
    monitor = DeviceMonitor(reg=reg)
    monitor.set_arena_source(
        lambda: {"lanes": 32, "lanes_busy": 24, "jobs_resident": 3}
    )
    sample = monitor.sample()
    assert sample["arena"]["occupancy"] == 0.75
    assert reg.value("mtpu_device_arena_lanes") == 32
    assert reg.value("mtpu_device_arena_lanes_busy") == 24
    assert reg.value("mtpu_device_arena_occupancy") == 0.75
    assert reg.value("mtpu_device_arena_jobs_resident") == 3
    # a broken source loses its block, never the sample
    monitor.set_arena_source(lambda: 1 / 0)
    sample = monitor.sample()
    assert "arena" not in sample
    assert sample["host_rss_bytes"] > 0


def test_wave_fractions_recomputed_from_explore_counters():
    reg = MetricsRegistry()
    monitor = DeviceMonitor(reg=reg)
    reg.counter("mtpu_explore_device_busy_s_total").inc(10.0)
    reg.counter("mtpu_explore_wave_overlap_s_total").inc(4.0)
    reg.counter("mtpu_explore_wall_s_total").inc(20.0)
    sample = monitor.sample()
    assert sample["wave_overlap_frac"] == pytest.approx(0.4)
    assert sample["idle_frac"] == pytest.approx(0.5)
    assert reg.value("mtpu_device_wave_overlap_frac") == pytest.approx(0.4)
    assert reg.value("mtpu_device_idle_frac") == pytest.approx(0.5)


def test_explore_publish_promotes_derived_ratio_gauges():
    """publish_explore_stats now lands the per-run derived ratios as
    live gauges (last run wins) beside the summed counters."""
    from mythril_tpu.laser.batch.explore import publish_explore_stats
    from mythril_tpu.observe.registry import registry

    publish_explore_stats(
        {"wave_overlap_ratio": 0.62, "device_idle_frac": 0.08}
    )
    assert registry().value(
        "mtpu_explore_wave_overlap_ratio"
    ) == pytest.approx(0.62)
    assert registry().value(
        "mtpu_explore_device_idle_frac"
    ) == pytest.approx(0.08)


def test_process_monitor_is_shared():
    assert device_monitor() is device_monitor()


def test_kernel_cache_gauges_present():
    reg = MetricsRegistry()
    sample = DeviceMonitor(reg=reg).sample()
    assert "kernel_cache" in sample
    text = reg.prometheus_text()
    assert "# TYPE mtpu_device_kernel_cache_size gauge" in text
    assert "# TYPE mtpu_device_kernel_compiles_in_flight gauge" in text
