"""Metric-cardinality budget guard (tier-1 `observe` marker).

Label explosions are the classic Prometheus regression: a label that
accidentally carries a job id, a code hash, or a per-request value
grows the registry without bound and kills every scrape. Nothing
guarded it until now. This test runs a REAL serve + analyze pass
against a fresh registry and then asserts every metric family's
label-set count stays inside a declared budget — adding a high-
cardinality label becomes a test failure, not a production incident.

The budgets are deliberately tight for this workload (one engine, a
handful of jobs, one analyzed contract): a family that needs more
series than its budget here is carrying a per-request label."""

from __future__ import annotations

import pytest

from mythril_tpu.observe.registry import registry, reset_registry
from mythril_tpu.service.client import ServiceClient
from mythril_tpu.service.engine import ServiceConfig
from mythril_tpu.service.server import AnalysisServer

pytestmark = [pytest.mark.observe, pytest.mark.service]

#: tiny branching contract (full wave path, no findings needed)
WRITER = "6001600055600160015560026000f3"
#: CALLER; SELFDESTRUCT — analyzable in one short walk
KILLABLE = "33ff"

#: per-family label-set budgets for THIS workload; everything not
#: listed gets the default. A budget is the declared cardinality
#: contract, not a generous ceiling — tighten when in doubt.
DEFAULT_BUDGET = 16
BUDGETS = {
    # reason x verdict waterfall (loss taxonomy is ~a dozen reasons)
    "mtpu_solver_loss_total": 48,
    # origin x verdict
    "mtpu_solver_queries_total": 24,
    # per-phase wall histogram (fixed phase vocabulary)
    "mtpu_phase_wall_seconds": 24,
    # objective x window burn gauges
    "mtpu_health_burn_rate": 24,
    # explorer counter families are label-less but numerous — they
    # appear as one series each and ride the default budget
}


def test_registry_cardinality_stays_inside_budget():
    reset_registry()
    try:
        # -- the serve half: admission, waves, settle, health --------
        config = ServiceConfig(
            stripes=2,
            lanes_per_stripe=4,
            steps_per_wave=32,
            max_waves=1,
            queue_capacity=4,
            host_walk=False,
            coalesce_wait_s=0.02,
            idle_wait_s=0.02,
            health_interval_s=0.1,
        )
        server = AnalysisServer(config).start()
        try:
            client = ServiceClient(server.url)
            for code in (WRITER, KILLABLE):
                job_id = client.submit(code)
                report = client.report(job_id, wait_s=120.0)
                assert report["state"] == "done", report
        finally:
            server.close()

        # -- the analyze half: host walk, solver, routing record -----
        from mythril_tpu.analysis.corpus import analyze_corpus

        results = analyze_corpus(
            [(KILLABLE, "", "Killable")],
            execution_timeout=8,
            create_timeout=5,
            processes=1,
            use_device=False,
        )
        assert results and results[0].get("error") is None

        snap = registry().snapshot()
        assert snap, "the run registered nothing — wrong registry?"
        over_budget = {
            name: len(series)
            for name, series in snap.items()
            if len(series) > BUDGETS.get(name, DEFAULT_BUDGET)
        }
        assert not over_budget, (
            "metric families exceeded their cardinality budget "
            f"(label explosion?): {over_budget}"
        )
        # the run must actually have exercised the families the guard
        # exists for — an empty snapshot proves nothing
        for expected in (
            "mtpu_service_waves_total",
            "mtpu_service_jobs_settled_total",
            "mtpu_service_job_latency_seconds",
            "mtpu_health_state",
        ):
            assert expected in snap, f"{expected} missing from the run"
    finally:
        # later suites get a fresh registry either way; engines from
        # this test keep writing to their own (orphaned) instance
        reset_registry()
