"""Solver query flight recorder suite (observe/querylog.py +
laser/smt/solver/capture.py; tier-1 `solverlab` marker).

Pins the ISSUE-8 capture half:
- serialize/deserialize roundtrip: rebuilt queries decide identically,
  content addresses are stable and var-name-canonical;
- the on-disk artifact schema golden + same-query dedup;
- loss-reason classification at every funnel exit site (gate off,
  sprint preemption, deterministic mode, trivial queries, the race
  losses — nonconverged vs timing vs invalid witness — via stubbed
  races), and the accounting identity: one sat-loss per CDCL sat;
- capture disabled by default, and the disabled path adds no registry
  series;
- loss counters are legacy-backing registry arithmetic: they stay on
  under --no-observe.
"""

import glob
import json
import os

import pytest

from mythril_tpu import observe
from mythril_tpu.laser.smt import terms
from mythril_tpu.laser.smt.solver import device_race, native_sat
from mythril_tpu.laser.smt.solver.solver import (
    check_terms,
    reset_blast_session,
    sat,
    unsat,
)
from mythril_tpu.laser.smt.solver.solver_statistics import SolverStatistics
from mythril_tpu.observe import querylog

pytestmark = pytest.mark.solverlab

_UNIQ = [0]


def _vars(n=1, width=16):
    """Fresh var names per call: the persistent blast session and the
    get_model memo key on names, and tests must not share state."""
    _UNIQ[0] += 1
    return [
        terms.bv_var(f"qlv{_UNIQ[0]}_{i}", width) for i in range(n)
    ]


def _range_query(lo=3, hi=9, width=16):
    (x,) = _vars(1, width)
    return [
        terms.ult(terms.bv_const(lo, width), x),
        terms.ult(x, terms.bv_const(hi, width)),
    ]


@pytest.fixture(autouse=True)
def _clean():
    from mythril_tpu.support.support_args import args

    restore = (args.device_solving, args.parallel_solving,
               args.deterministic_solving)
    querylog.configure_capture(None)
    yield
    (args.device_solving, args.parallel_solving,
     args.deterministic_solving) = restore
    querylog.configure_capture(None)
    observe.set_enabled(True)


# -- serialization ----------------------------------------------------------


def test_roundtrip_preserves_verdicts():
    from mythril_tpu.laser.smt.solver.preprocess import lower

    query = _range_query()
    live, _ = check_terms(query)
    lowered, _recon = lower(query)
    doc = querylog.serialize_terms(lowered)
    rebuilt = querylog.deserialize_terms(doc)
    replayed, _ = check_terms(rebuilt)
    assert live == replayed == sat

    # an unsat query roundtrips to unsat
    (y,) = _vars()
    contradiction = [
        terms.ult(y, terms.bv_const(3, 16)),
        terms.ult(terms.bv_const(7, 16), y),
    ]
    assert check_terms(contradiction)[0] == unsat
    doc2 = querylog.serialize_terms(contradiction)
    assert check_terms(querylog.deserialize_terms(doc2))[0] == unsat


def test_roundtrip_covers_the_lowered_op_surface():
    """Every op family the preprocessor can leave behind survives the
    roundtrip as the SAME interned term."""
    x, y = _vars(2, 64)
    b = terms.bool_var(f"qlb{_UNIQ[0]}")
    query = [
        terms.eq(
            terms.add(terms.mul(x, y), terms.udiv(x, terms.bv_const(3, 64))),
            terms.bvxor(terms.shl(x, terms.bv_const(2, 64)), terms.bvnot(y)),
        ),
        terms.band(
            b,
            terms.bor(
                terms.slt(terms.sext(terms.extract(7, 0, x), 8), y),
                terms.ule(terms.concat(terms.extract(15, 8, x),
                                       terms.extract(7, 0, y)), x),
            ),
        ),
        terms.eq(
            terms.ite(b, terms.urem(x, terms.bv_const(5, 64)),
                      terms.ashr(y, terms.bv_const(1, 64))),
            terms.zext(terms.extract(31, 0, x), 32),
        ),
    ]
    doc = querylog.serialize_terms(query)
    rebuilt = querylog.deserialize_terms(doc)
    # interning makes identity the strongest possible equality
    assert all(a is b_ for a, b_ in zip(query, rebuilt))


def test_content_address_stable_and_name_canonical():
    query = _range_query()
    doc = querylog.serialize_terms(query)
    assert querylog.content_address(doc) == querylog.content_address(
        querylog.serialize_terms(query)
    )
    # same shape under different var NAMES -> same address (the
    # preprocessor gensyms fresh names run to run)
    (z,) = _vars()
    renamed = [
        terms.ult(terms.bv_const(3, 16), z),
        terms.ult(z, terms.bv_const(9, 16)),
    ]
    assert querylog.content_address(
        querylog.serialize_terms(renamed)
    ) == querylog.content_address(doc)
    # a different CONSTANT is a different query
    (w,) = _vars()
    other = [
        terms.ult(terms.bv_const(4, 16), w),
        terms.ult(w, terms.bv_const(9, 16)),
    ]
    assert querylog.content_address(
        querylog.serialize_terms(other)
    ) != querylog.content_address(doc)


# -- capture ----------------------------------------------------------------


def test_capture_disabled_by_default(tmp_path):
    assert not querylog.capture_enabled()
    marker = observe.registry().marker()
    check_terms(_range_query())
    delta = observe.registry().since(marker)
    assert not delta.get("mtpu_solver_captured_queries_total")


def test_artifact_schema_and_dedup(tmp_path):
    querylog.configure_capture(str(tmp_path))
    query = _range_query()
    check_terms(query)
    check_terms(query)  # identical content -> one artifact, two obs
    files = glob.glob(str(tmp_path / "q-*.json"))
    assert len(files) == 1
    with open(files[0]) as fp:
        artifact = json.load(fp)
    assert artifact["schema_version"] == querylog.ARTIFACT_SCHEMA_VERSION
    assert artifact["kind"] == "mtpu-solver-query"
    assert os.path.basename(files[0]) == f"q-{artifact['sha']}.json"
    assert artifact["origin"] == "memo-miss"  # bare check_terms
    assert artifact["verdict"] == sat
    assert artifact["loss_reason"]  # host-won: reason is non-empty
    assert artifact["n_constraints"] == 2
    assert set(artifact["bucket"]) == {
        "nodes", "consts", "roots", "vars", "limbs"
    }
    assert artifact["compile_loss"] is None
    assert len(artifact["observations"]) == 2
    obs = artifact["observations"][0]
    assert set(obs) == {
        "engine", "verdict", "wall_s", "hop", "loss_reason", "site"
    }
    assert obs["engine"] == "host-cdcl"
    # the corpus loader round-trips it
    corpus = querylog.load_corpus(str(tmp_path))
    assert len(corpus) == 1 and corpus[0]["sha"] == artifact["sha"]


def test_capture_respects_query_context(tmp_path):
    querylog.configure_capture(str(tmp_path))
    with querylog.query_context("flip-frontier"):
        check_terms(_range_query(lo=3, hi=9))
    with querylog.query_context("module"):
        # memo-miss must NOT mask an enclosing module tag
        with querylog.query_context("memo-miss", only_if_root=True):
            check_terms(_range_query(lo=4, hi=11))
    origins = sorted(
        a["origin"] for a in querylog.load_corpus(str(tmp_path))
    )
    assert origins == ["flip-frontier", "module"]


def test_dedup_keeps_the_first_origin(tmp_path):
    """Structurally-identical queries from two contexts land in ONE
    content-addressed artifact; the origin recorded is the first
    capturer's (observations keep accruing)."""
    querylog.configure_capture(str(tmp_path))
    with querylog.query_context("flip-frontier"):
        check_terms(_range_query(lo=5, hi=12))
    with querylog.query_context("module"):
        check_terms(_range_query(lo=5, hi=12))  # same canonical shape
    corpus = querylog.load_corpus(str(tmp_path))
    assert len(corpus) == 1
    assert corpus[0]["origin"] == "flip-frontier"
    assert len(corpus[0]["observations"]) == 2


# -- loss classification at the funnel exits --------------------------------


def _sat_losses(marker):
    return querylog.loss_reasons(since=marker, verdict="sat")


def test_gate_disabled_and_accounting_identity():
    from mythril_tpu.support.support_args import args

    args.device_solving = "never"
    marker = observe.registry().marker()
    base = SolverStatistics().cdcl_sat_count
    check_terms(_range_query())
    check_terms(_range_query())
    losses = _sat_losses(marker)
    assert losses == {"GATE_DISABLED": 2}
    assert sum(losses.values()) == SolverStatistics().cdcl_sat_count - base


def test_sprint_preempted_when_gate_open():
    from mythril_tpu.support.support_args import args

    args.device_solving = "always"
    marker = observe.registry().marker()
    check_terms(_range_query())
    assert _sat_losses(marker) == {"SPRINT_PREEMPTED": 1}


def test_deterministic_mode_counts_as_gate_disabled():
    from mythril_tpu.support.support_args import args

    args.device_solving = "always"
    args.deterministic_solving = True
    marker = observe.registry().marker()
    check_terms(_range_query())
    assert _sat_losses(marker) == {"GATE_DISABLED": 1}


def test_trivial_unsat_is_not_a_loss():
    marker = observe.registry().marker()
    base = SolverStatistics().cdcl_sat_count
    verdict, _ = check_terms([terms.FALSE])
    assert verdict == unsat
    assert _sat_losses(marker) == {}
    all_losses = querylog.loss_reasons(since=marker)
    assert all_losses == {"QUERY_TRIVIAL": 1}
    assert SolverStatistics().cdcl_sat_count == base


def _force_sprint_unknown(monkeypatch):
    """First native solve (the sprint) comes back UNKNOWN; later calls
    run for real — the query drops into the race/marathon branch."""
    real = native_sat.SolverSession.solve
    calls = {"n": 0}

    def fake(self, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            return native_sat.UNKNOWN, None
        return real(self, *a, **kw)

    monkeypatch.setattr(native_sat.SolverSession, "solve", fake)


def _race_stub(poll_result, outcome):
    class StubRace:
        started = True

        def __init__(self, lowered, **kw):
            pass

        def poll(self):
            return poll_result

        def outcome(self):
            return outcome

    return StubRace


def test_race_nonconverged_vs_timing_vs_invalid(monkeypatch):
    """The satellite pin: race_losses split into 'portfolio finished
    without a witness' (SLS_NONCONVERGED), 'still running when the
    CDCL answered' (RACE_LOST_TIMING), and 'witness failed the gate'
    (WITNESS_INVALID)."""
    from mythril_tpu.laser.smt.solver import solver as solver_mod
    from mythril_tpu.support.support_args import args

    args.device_solving = "always"

    del solver_mod  # the impl imports device_race afresh per query
    cases = [
        (_race_stub(device_race.FAILED, "failed"), "SLS_NONCONVERGED"),
        (_race_stub(device_race.PENDING, "pending"), "RACE_LOST_TIMING"),
        (_race_stub({"bogus_var": 1}, "witness"), "WITNESS_INVALID"),
    ]
    for stub, expected in cases:
        _force_sprint_unknown(monkeypatch)
        monkeypatch.setattr(device_race, "DeviceRace", stub, raising=True)
        losses_before = SolverStatistics().race_losses
        marker = observe.registry().marker()
        verdict, _ = check_terms(_range_query())
        assert verdict == sat
        assert _sat_losses(marker) == {expected: 1}, expected
        assert SolverStatistics().race_losses == losses_before + 1
        monkeypatch.undo()
        reset_blast_session()


def test_race_not_started_when_chip_busy(monkeypatch):
    from mythril_tpu.support.support_args import args

    args.device_solving = "always"
    _force_sprint_unknown(monkeypatch)
    monkeypatch.setattr(device_race, "race_available", lambda: False)
    marker = observe.registry().marker()
    verdict, _ = check_terms(_range_query())
    assert verdict == sat
    assert _sat_losses(marker) == {"RACE_NOT_STARTED": 1}
    monkeypatch.undo()
    reset_blast_session()


def test_loss_counters_survive_no_observe():
    """record_loss is legacy-backing registry arithmetic: the bench
    identity must hold with telemetry off."""
    from mythril_tpu.support.support_args import args

    args.device_solving = "never"
    observe.set_enabled(False)
    try:
        marker = observe.registry().marker()
        check_terms(_range_query())
        assert _sat_losses(marker) == {"GATE_DISABLED": 1}
    finally:
        observe.set_enabled(True)


# -- the folded SolverStatistics singleton ----------------------------------


def test_solver_statistics_is_a_registry_view():
    stats = SolverStatistics()
    reg = observe.registry()
    before = stats.device_cert_count
    stats.device_cert_count += 2
    assert stats.device_cert_count == before + 2
    assert (
        reg.value("mtpu_solver_stats_device_certs_total") == before + 2
    )
    wins_before = reg.value("mtpu_solver_stats_race_total", outcome="won")
    stats.race_wins += 1
    assert reg.value(
        "mtpu_solver_stats_race_total", outcome="won"
    ) == wins_before + 1
    # the repr keeps its legacy shape
    text = repr(stats)
    assert text.startswith("Solver statistics:")
    for line in (
        "Query count:", "Solver time:",
        "Sat verdicts from device portfolio:", "Sat verdicts from CDCL:",
        "Device races won/lost:",
    ):
        assert line in text
