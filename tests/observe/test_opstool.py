"""`myth observe` operator tooling (observe/opstool.py, tier-1
`observe` marker): the Prometheus text parser, the top/report
renderers, and the bench-record compare gate — including the
acceptance contract that the committed BENCH_r04 -> r06 trajectory
reproduces clean while an injected regression exits the gate dirty."""

from __future__ import annotations

import json
import os

import pytest

from mythril_tpu.observe import opstool

pytestmark = pytest.mark.observe

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def bench_path(n: int) -> str:
    return os.path.join(REPO, f"BENCH_r{n:02d}.json")


def test_parse_prometheus_families_and_labels():
    text = "\n".join([
        "# HELP mtpu_x_total help",
        "# TYPE mtpu_x_total counter",
        'mtpu_x_total{origin="host-cdcl",verdict="sat"} 3',
        'mtpu_x_total{origin="memo",verdict="sat"} 2',
        "mtpu_health_state 1",
        "junk line without a value",
    ])
    parsed = opstool.parse_prometheus(text)
    assert opstool.family_total(parsed, "mtpu_x_total") == 5
    assert opstool.family_total(
        parsed, "mtpu_x_total", origin="memo"
    ) == 2
    assert opstool.family_total(parsed, "mtpu_health_state") == 1


def test_render_top_shows_health_queue_and_tiers():
    stats = {
        "uptime_s": 12.5,
        "health": {
            "state": "degraded",
            "ready": False,
            "reasons": ["slo-degraded:warm-settle-p95"],
            "not_ready_reasons": ["arena-warming"],
            "objectives": [
                {"objective": "warm-settle-p95", "state": "degraded",
                 "burn_short": 2.5, "burn_long": 1.2},
            ],
        },
        "queue": {"depth": 4, "capacity": 8, "accepted": 30,
                  "rejected_full": 1, "rejected_draining": 0,
                  "jobs": {"done": 25, "failed": 1}},
        "arena": {"lanes": 32, "lanes_busy": 16, "jobs_resident": 2,
                  "max_jobs_resident": 4},
        "waves": {"count": 90, "rate_per_s": 12.0,
                  "warm_wave_s": 0.01, "cold_wave_s": 4.2},
        "store": {"answered": 7},
        "static": {"static_answered": 3},
        "solver": {"loss": {"GATE_DISABLED": 10}},
        "device": {
            "arena": {"occupancy": 0.5},
            "host_rss_bytes": 200 << 20,
            "wave_overlap_frac": 0.4,
            "kernel_cache": {"size": 2, "pinned": 1},
        },
    }
    frame = opstool.render_top(stats)
    assert "DEGRADED" in frame
    assert "arena-warming" in frame
    assert "warm-settle-p95" in frame
    assert "4/8" in frame  # queue bar
    assert "store-hit=7" in frame and "static-answer=3" in frame
    assert "GATE_DISABLED=10" in frame
    assert "overlap=0.4" in frame


def test_render_report_markdown_and_html():
    routing = [
        {"outcome": {"route": "store-hit", "wall_s": 0.002}},
        {"outcome": {"route": "store-hit", "wall_s": 0.003}},
        {"outcome": {"route": "host-walk", "wall_s": 2.5}},
    ]
    journeys = [
        {"journey_id": "abc", "tiers": ["admission", "settle"],
         "wall_s": 0.01},
    ]
    md = opstool.render_report(
        routing_records=routing, journeys=journeys,
    )
    assert "| store-hit | 2 |" in md
    assert "| host-walk | 1 |" in md
    assert "admission -> settle" in md
    html = opstool.render_report(
        routing_records=routing, fmt="html"
    )
    assert html.startswith("<!doctype html>")
    assert "store-hit" in html


def test_compare_reproduces_r04_to_r06_trajectory():
    """The acceptance contract: the committed records gate clean, r05
    (parsed=null, the timed-out TPU round) is skipped with a note,
    and the stable-field trajectory is present."""
    records = [
        opstool.load_bench_record(bench_path(n)) for n in (4, 5, 6)
    ]
    result = opstool.compare_records(records)
    assert result["labels"] == ["r04", "r06"]
    assert result["skipped"] == ["r05"]
    assert result["regressions"] == []
    traj = result["trajectory"]["scaling_ratio_4x_steps"]
    assert traj == [3.62, 3.81]
    rendered = opstool.render_compare(result)
    assert "r04 -> r06" in rendered
    assert "no regressions on stable fields" in rendered
    # cross-backend fields ride the table but are exempt from gating
    assert "device_verdict_share" in result["exempt_fields"]


def test_compare_full_committed_history_gates_clean():
    records = [
        opstool.load_bench_record(bench_path(n)) for n in range(1, 7)
    ]
    result = opstool.compare_records(records)
    assert result["regressions"] == []


def test_injected_regression_fails_the_gate(tmp_path):
    _label, r06 = opstool.load_bench_record(bench_path(6))
    bad = dict(r06, scaling_ratio_4x_steps=1.0, store_hit_rate=0.1)
    path = tmp_path / "BENCH_bad.json"
    path.write_text(json.dumps({"n": 7, "parsed": bad}))
    records = [
        opstool.load_bench_record(bench_path(6)),
        opstool.load_bench_record(str(path)),
    ]
    result = opstool.compare_records(records)
    fields = {r["field"] for r in result["regressions"]}
    assert "scaling_ratio_4x_steps" in fields
    assert "store_hit_rate" in fields
    rendered = opstool.render_compare(result)
    assert "REGRESSION scaling_ratio_4x_steps" in rendered
    # a lower-is-better regression: warm hits getting slower
    worse = dict(r06, warm_hit_p50_s=0.5)
    path.write_text(json.dumps({"n": 7, "parsed": worse}))
    result = opstool.compare_records([
        opstool.load_bench_record(bench_path(6)),
        opstool.load_bench_record(str(path)),
    ])
    assert {r["field"] for r in result["regressions"]} == {
        "warm_hit_p50_s"
    }


def test_threshold_scale_loosens_the_gate(tmp_path):
    _label, r06 = opstool.load_bench_record(bench_path(6))
    slightly_worse = dict(
        r06, scaling_ratio_4x_steps=r06["scaling_ratio_4x_steps"] * 0.8
    )
    path = tmp_path / "BENCH_meh.json"
    path.write_text(json.dumps({"n": 7, "parsed": slightly_worse}))
    records = [
        opstool.load_bench_record(bench_path(6)),
        opstool.load_bench_record(str(path)),
    ]
    assert opstool.compare_records(records)["regressions"]
    assert not opstool.compare_records(
        records, threshold_scale=2.0
    )["regressions"]


def test_observe_cli_command_registered():
    from mythril_tpu.interfaces.cli import COMMAND_LIST, build_parser

    assert "observe" in COMMAND_LIST
    parser = build_parser()
    args = parser.parse_args(
        ["observe", "compare", "a.json", "b.json", "--fail-on-regression"]
    )
    assert args.command == "observe"
    assert args.observe_mode == "compare"
    assert args.records == ["a.json", "b.json"]
    assert args.fail_on_regression is True
