"""Unified telemetry layer suite (mythril_tpu/observe, tier-1
`observe` marker).

Pins the four surfaces the ISSUE-7 tentpole built:
- metrics registry: counter/gauge/histogram semantics, label sets,
  single-lock snapshots + per-run deltas, Prometheus exposition golden;
- structured spans: nesting/ordering under threads, the flight
  recorder's bounds, Perfetto trace-event schema, overlap fraction,
  the automatic dump on an injected mesh degradation;
- solver attribution: per-origin tables with markers;
- routing feature log: JSONL schema golden;
plus the satellites: ExploreStats merge-policy completeness, the
registry-vs-legacy-view equality on a real explorer run, the
registry-backed PhaseProfile's byte-compatible view, and the service
/stats schema_version + /metrics + /trace endpoints."""

import json
import os
import threading
import time

import pytest

from mythril_tpu import observe
from mythril_tpu.observe.registry import (
    SCHEMA_VERSION,
    MetricsRegistry,
    registry,
)
from mythril_tpu.observe.spans import (
    FlightRecorder,
    Span,
    flight_recorder,
    overlap_fraction,
    to_perfetto,
    trace,
)

pytestmark = pytest.mark.observe

#: tiny runtime: a dispatcher with one selector and an INVALID body —
#: enough for the explorer to cover branches and bank a trigger
TINY = (
    "6080604052348015600f57600080fd5b50600436106028576000"
    "3560e01c8063c0406226146028575b600080fd5b60306032565b005b6000fe"
)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    c.labels(kind="a").inc(4)
    assert c.labels(kind="a").value == 4
    assert c.value == 3.5  # label-less series unaffected

    g = reg.gauge("t_gauge")
    g.set(7)
    g.set_max(3)
    assert g.value == 7
    g.set_max(11)
    assert g.value == 11

    h = reg.histogram("t_hist", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    child = h.labels()
    assert child.count == 3
    assert abs(child.sum - 5.55) < 1e-9

    with pytest.raises(ValueError):
        reg.gauge("t_total")  # kind conflict


def test_snapshot_and_since_deltas():
    reg = MetricsRegistry()
    c = reg.counter("d_total")
    c.inc(5)
    marker = reg.marker()
    c.inc(2)
    reg.gauge("d_gauge").set(9)
    delta = reg.since(marker)
    assert delta["d_total"][()] == 2
    assert delta["d_gauge"][()] == 9  # gauges report current value
    # unchanged counters drop out of the delta entirely
    c2 = reg.counter("d_idle_total")
    c2.inc(1)
    marker2 = reg.marker()
    assert "d_idle_total" not in reg.since(marker2)


def test_snapshot_is_single_lock_consistent_under_writers():
    """Racing writers always bump two counters together; every
    snapshot must see them EQUAL — the /stats atomicity contract."""
    reg = MetricsRegistry()
    a = reg.counter("pair_a_total")
    b = reg.counter("pair_b_total")
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            with reg._lock:
                a.inc()
                b.inc()

    threads = [threading.Thread(target=writer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            snap = reg.snapshot()
            assert snap["pair_a_total"].get((), 0) == snap[
                "pair_b_total"
            ].get((), 0)
    finally:
        stop.set()
        for t in threads:
            t.join()


def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    reg.counter("g_requests_total", "requests served").labels(
        route="/stats"
    ).inc(3)
    reg.gauge("g_depth", "queue depth").set(2)
    h = reg.histogram("g_wall_seconds", "wall", buckets=(0.5, 2.0))
    h.observe(0.25)
    h.observe(1.0)
    h.observe(9.0)
    assert reg.prometheus_text() == (
        "# HELP g_depth queue depth\n"
        "# TYPE g_depth gauge\n"
        "g_depth 2\n"
        "# HELP g_requests_total requests served\n"
        "# TYPE g_requests_total counter\n"
        'g_requests_total{route="/stats"} 3\n'
        "# HELP g_wall_seconds wall\n"
        "# TYPE g_wall_seconds histogram\n"
        'g_wall_seconds_bucket{le="0.5"} 1\n'
        'g_wall_seconds_bucket{le="2"} 2\n'
        'g_wall_seconds_bucket{le="+Inf"} 3\n'
        "g_wall_seconds_sum 10.25\n"
        "g_wall_seconds_count 3\n"
    )


def test_collector_samples_merge_into_snapshot():
    reg = MetricsRegistry()
    reg.collector(lambda: [("ext_depth", {"q": "main"}, 4)])
    snap = reg.snapshot()
    assert snap["ext_depth"][(("q", "main"),)] == 4


# ---------------------------------------------------------------------------
# per-metric histogram bucket overrides (ISSUE 12)
# ---------------------------------------------------------------------------
def test_histogram_bucket_override_semantics():
    from mythril_tpu.observe.registry import DEFAULT_BUCKETS

    reg = MetricsRegistry()
    # a default-bucket registration followed by an explicit override
    # while the series is still empty: the override wins
    h = reg.histogram("ob_wall_seconds")
    assert h.buckets == DEFAULT_BUCKETS
    h = reg.histogram("ob_wall_seconds", buckets=(0.001, 0.01, 0.1))
    assert h.buckets == (0.001, 0.01, 0.1)
    assert reg.buckets_of("ob_wall_seconds") == (0.001, 0.01, 0.1)
    # a later DEFAULT-bucket re-registration (a generic call site)
    # never clobbers the explicit ladder
    h = reg.histogram("ob_wall_seconds")
    assert h.buckets == (0.001, 0.01, 0.1)
    # once observations exist, a conflicting explicit ladder is
    # ignored — bucket counts are meaningless across a switch
    h.observe(0.05)
    h = reg.histogram("ob_wall_seconds", buckets=(1.0, 2.0))
    assert h.buckets == (0.001, 0.01, 0.1)


def test_job_latency_rebucket_exposition_golden():
    """The re-bucketed job-latency ladder: a ~1.9ms store hit and a
    ~21s cold walk (the BENCH_r06 spectrum) land in DISTINCT buckets
    — the default ladder crushed everything under 5ms into one. The
    exposition is pinned exactly."""
    from mythril_tpu.observe.registry import LATENCY_BUCKETS

    reg = MetricsRegistry()
    h = reg.histogram(
        "jl_latency_seconds", "submit-to-terminal latency",
        buckets=LATENCY_BUCKETS,
    )
    h.observe(0.0019)  # the warm store hit
    h.observe(0.0021)  # a second warm settle
    h.observe(21.0)  # the cold walk
    text = reg.prometheus_text()
    assert text == (
        "# HELP jl_latency_seconds submit-to-terminal latency\n"
        "# TYPE jl_latency_seconds histogram\n"
        'jl_latency_seconds_bucket{le="0.0005"} 0\n'
        'jl_latency_seconds_bucket{le="0.001"} 0\n'
        'jl_latency_seconds_bucket{le="0.002"} 1\n'
        'jl_latency_seconds_bucket{le="0.005"} 2\n'
        'jl_latency_seconds_bucket{le="0.01"} 2\n'
        'jl_latency_seconds_bucket{le="0.025"} 2\n'
        'jl_latency_seconds_bucket{le="0.05"} 2\n'
        'jl_latency_seconds_bucket{le="0.1"} 2\n'
        'jl_latency_seconds_bucket{le="0.25"} 2\n'
        'jl_latency_seconds_bucket{le="0.5"} 2\n'
        'jl_latency_seconds_bucket{le="1"} 2\n'
        'jl_latency_seconds_bucket{le="2.5"} 2\n'
        'jl_latency_seconds_bucket{le="5"} 2\n'
        'jl_latency_seconds_bucket{le="10"} 2\n'
        'jl_latency_seconds_bucket{le="30"} 3\n'
        'jl_latency_seconds_bucket{le="60"} 3\n'
        'jl_latency_seconds_bucket{le="120"} 3\n'
        'jl_latency_seconds_bucket{le="+Inf"} 3\n'
        "jl_latency_seconds_sum 21.004\n"
        "jl_latency_seconds_count 3\n"
    )


def test_service_and_solver_histograms_ride_their_ladders():
    """The two production histograms the satellite re-buckets: the
    service job-latency series and the per-query solver wall."""
    from mythril_tpu.observe.registry import (
        LATENCY_BUCKETS,
        SOLVER_WALL_BUCKETS,
        registry as global_registry,
    )
    from mythril_tpu.service.jobs import Job, JobQueue

    queue = JobQueue(4)
    job = Job(code_hex="6001")
    queue.submit(job)
    queue.settle(job, "done")
    assert global_registry().buckets_of(
        "mtpu_service_job_latency_seconds"
    ) == LATENCY_BUCKETS

    observe.record_query("host-cdcl", "sat", wall_s=0.002)
    assert global_registry().buckets_of(
        "mtpu_solver_query_seconds"
    ) == SOLVER_WALL_BUCKETS


# ---------------------------------------------------------------------------
# spans + flight recorder
# ---------------------------------------------------------------------------
def test_span_nesting_and_ordering_under_threads():
    recorder = flight_recorder()
    base = recorder.recorded
    seen = {}

    def work(tag):
        with trace(f"outer.{tag}"):
            with trace(f"inner.{tag}", step=1):
                time.sleep(0.01)
        seen[tag] = True

    threads = [
        threading.Thread(target=work, args=(i,), name=f"obs-w{i}")
        for i in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = [
        s
        for s in recorder.tail(2048)
        if s.name.startswith(("outer.", "inner."))
    ]
    assert recorder.recorded - base >= 6
    by_name = {s.name: s for s in spans}
    for i in range(3):
        inner, outer = by_name[f"inner.{i}"], by_name[f"outer.{i}"]
        # nesting: the inner span's parent is ITS thread's outer span
        assert inner.parent == outer.sid
        assert outer.parent is None
        assert inner.tid == outer.tid == f"obs-w{i}"
        # ordering: children open after and close before their parent
        assert outer.t0 <= inner.t0 <= inner.t1 <= outer.t1
        assert inner.attrs == {"step": 1}


def test_trace_disabled_records_nothing():
    recorder = flight_recorder()
    observe.set_enabled(False)
    try:
        base = recorder.recorded
        with trace("never.recorded"):
            pass
        recorder.add("never.recorded.retro", 0.0, 1.0)
        assert recorder.recorded == base
    finally:
        observe.set_enabled(True)


def test_flight_recorder_is_bounded():
    recorder = FlightRecorder(capacity=32)
    for i in range(100):
        recorder.record(Span(i, None, "s", 0.0, 1.0, "t", None, None))
    assert len(recorder) == 32
    assert recorder.dropped == 100 - 32
    assert [s.sid for s in recorder.tail(3)] == [97, 98, 99]


def test_perfetto_trace_event_schema():
    spans = [
        Span(1, None, "wave.device", 10.0, 10.5, "main", "mesh-g0", None),
        Span(2, 1, "wave.harvest", 10.1, 10.2, "main", None, {"serial": 3}),
    ]
    doc = to_perfetto(spans)
    events = doc["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(complete) == 2 and meta, events
    for e in complete:
        # the trace-event contract Perfetto loads: integral µs
        # timestamps/durations, pid/tid tracks, a name
        assert isinstance(e["ts"], int) and e["ts"] >= 0
        assert isinstance(e["dur"], int) and e["dur"] >= 1
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["name"]
    # the device-group track gets its own labeled thread
    names = {e["args"]["name"] for e in meta}
    assert "mesh-g0" in names and "main" in names
    # json-serializable end to end
    json.dumps(doc)


def test_overlap_fraction():
    def span(t0, t1):
        return Span(0, None, "wave.device", t0, t1, "t", None, None)

    # [0,10] and [5,15]: covered 15s, overlapped 5s
    assert overlap_fraction([span(0, 10), span(5, 15)]) == round(5 / 15, 4)
    # disjoint spans never overlap
    assert overlap_fraction([span(0, 1), span(2, 3)]) == 0.0
    # a lone span has nothing to overlap with
    assert overlap_fraction([span(0, 10)]) == 0.0


def test_flight_dump_on_injected_mesh_degradation(tmp_path):
    """A MESH_GROUP_DEGRADED record auto-dumps the flight recorder
    into the observe directory (the post-mortem timeline)."""
    from mythril_tpu.parallel.topology import FailureDomain

    observe.reset_auto_dumps()
    observe.configure(out_dir=str(tmp_path))
    try:
        with trace("pre.fault"):
            pass
        FailureDomain(0).record_degraded(2, detail="injected by test")
        dumps = [
            f for f in os.listdir(tmp_path)
            if f.startswith("flight-mesh-group-degraded")
        ]
        assert dumps, os.listdir(tmp_path)
        doc = json.loads((tmp_path / dumps[0]).read_text())
        assert doc["traceEvents"]
        # the mesh fault also moved the registry's per-group counters
        assert (
            registry().value(
                "mtpu_mesh_group_faults_total", group="mesh-g0"
            )
            >= 1
        )
    finally:
        observe.configure(out_dir=None)


# ---------------------------------------------------------------------------
# solver attribution
# ---------------------------------------------------------------------------
def test_solver_attribution_table():
    marker = observe.solver_marker()
    observe.record_query("host-cdcl", "sat", 0.25)
    observe.record_query("host-cdcl", "unsat", 0.05)
    observe.record_query("device-portfolio", "sat", 1.5, hop=1)
    table = observe.solver_attribution(marker)
    assert table["host-cdcl"]["queries"] == 2
    assert table["host-cdcl"]["verdicts"] == {"sat": 1, "unsat": 1}
    assert abs(table["host-cdcl"]["wall_s"] - 0.3) < 1e-6
    assert table["device-portfolio"]["escalations"] == 1
    # disabled: nothing records
    observe.set_enabled(False)
    try:
        marker2 = observe.solver_marker()
        observe.record_query("host-cdcl", "sat", 1.0)
        assert observe.solver_attribution(marker2) == {}
    finally:
        observe.set_enabled(True)


def test_check_terms_records_attribution():
    """The real solver funnel tags its verdicts: a trivial UNSAT pair
    through check_terms lands in the host-cdcl row."""
    from mythril_tpu.laser.smt import terms
    from mythril_tpu.laser.smt.solver.solver import check_terms

    x = terms.bv_var("obs_x", 8)
    marker = observe.solver_marker()
    verdict, _model = check_terms(
        [terms.eq(x, terms.bv_const(1, 8)),
         terms.eq(x, terms.bv_const(2, 8))],
        timeout_ms=5000,
    )
    assert verdict == "unsat"
    table = observe.solver_attribution(marker)
    assert table["host-cdcl"]["verdicts"].get("unsat", 0) >= 1


# ---------------------------------------------------------------------------
# routing feature log
# ---------------------------------------------------------------------------
def test_routing_record_jsonl_schema(tmp_path):
    from mythril_tpu.observe.routing import RECORD_KEYS

    observe.configure(out_dir=str(tmp_path))
    try:
        rec = observe.routing_log().record(
            contract="Tiny",
            code_hash="ab" * 32,
            features=observe.routing_features_for(TINY),
            outcome=observe.routing_outcome_for(
                {
                    "name": "Tiny",
                    "issues": [{"swc-id": "110"}],
                    "states": 12,
                    "wall_s": 0.5,
                    "error": None,
                    "complete": True,
                    "owned": True,
                }
            ),
        )
        line = (tmp_path / "routing_features.jsonl").read_text()
        parsed = json.loads(line.strip().splitlines()[-1])
    finally:
        observe.configure(out_dir=None)
    from mythril_tpu.observe.routing import (
        SCHEMA_VERSION as ROUTING_SCHEMA_VERSION,
    )

    assert tuple(sorted(parsed)) == tuple(sorted(RECORD_KEYS))
    assert parsed == json.loads(json.dumps(rec, sort_keys=True))
    # the routing log versions its records independently of the
    # registry schema (v2 added the taint/value-set feature block)
    assert parsed["schema_version"] == ROUTING_SCHEMA_VERSION
    feats = parsed["features"]
    # the cost-model features ROADMAP item 5 trains on
    for key in ("code_bytes", "storage_op_density", "call_op_density"):
        assert key in feats, feats
    out = parsed["outcome"]
    assert out["route"] == "device-owned"
    assert out["issues"] == 1 and out["wall_s"] == 0.5


def test_routing_route_classification():
    assert (
        observe.routing_outcome_for({"skipped": "deadline-expired"})["route"]
        == "skipped"
    )
    assert (
        observe.routing_outcome_for({"owned": True})["route"]
        == "device-owned"
    )
    assert observe.routing_outcome_for({})["route"] == "host-walk"


# ---------------------------------------------------------------------------
# ExploreStats merge policy (the counter-drift satellite)
# ---------------------------------------------------------------------------
def test_merge_policy_covers_every_field():
    from mythril_tpu.laser.batch.explore import MERGE_POLICY, ExploreStats

    fields = set(ExploreStats().as_dict())
    policy = set(MERGE_POLICY)
    # every stat field has an EXPLICIT policy; the only extra policy
    # entry is the optional halt_reason the stats dict may carry
    assert fields - policy == set(), f"unmapped stats: {fields - policy}"
    assert policy - fields == {"halt_reason"}, policy - fields
    assert set(MERGE_POLICY.values()) <= {"sum", "max", "last", "derived"}


def test_merge_stats_semantics():
    from mythril_tpu.laser.batch.explore import merge_stats

    dst = {}
    merge_stats(dst, {
        "waves": 3, "arena_nodes": 10, "wall_s": 5.0,
        "halt_reason": "stop-event", "pipelined": 1,
    })
    merge_stats(dst, {
        "waves": 2, "arena_nodes": 7, "wall_s": 9.0,
        "halt_reason": "deadline-expired", "pipelined": 0,
    })
    assert dst["waves"] == 5  # sum
    assert dst["arena_nodes"] == 10  # max
    assert "wall_s" not in dst  # derived: recomputed by the caller
    assert dst["halt_reason"] == "deadline-expired"  # last
    assert dst["pipelined"] == 1  # max: any pipelined chunk marks it


def test_scheduler_merge_rides_the_policy():
    """The mesh scheduler's fold uses the explicit policy (this is the
    drift regression: a summed high-water mark would exceed the max)."""
    from mythril_tpu.parallel.scheduler import CorpusScheduler

    sched = CorpusScheduler.__new__(CorpusScheduler)
    sched._merged_stats = {}
    sched._merge_stats({"waves": 1, "waves_inflight_max": 2, "spec_pruned_phases": 5})
    sched._merge_stats({"waves": 1, "waves_inflight_max": 2, "spec_pruned_phases": 3})
    assert sched._merged_stats["waves"] == 2
    assert sched._merged_stats["waves_inflight_max"] == 2
    assert sched._merged_stats["spec_pruned_phases"] == 5


# ---------------------------------------------------------------------------
# PhaseProfile: registry-backed view, byte-compatible shape
# ---------------------------------------------------------------------------
def test_phase_profile_view_and_registry_backing():
    from mythril_tpu.support.phase_profile import PhaseProfile

    profile = PhaseProfile()
    profile.reset()
    hist = registry().histogram("mtpu_phase_wall_seconds")
    before = hist.labels(phase="obs_test").count
    with profile.measure("obs_test"):
        pass
    profile.add("obs_test", 0.75, n=2)
    snap = profile.as_dict()
    assert snap["obs_test"]["count"] == 3
    assert snap["obs_test"]["wall_s"] >= 0.75
    assert "obs_test" in str(profile)
    # the registry kept the cumulative series (the /metrics view)...
    assert hist.labels(phase="obs_test").count == before + 3
    # ...while the per-contract view resets to empty
    profile.reset()
    assert profile.as_dict() == {}
    assert hist.labels(phase="obs_test").count == before + 3


# ---------------------------------------------------------------------------
# registry-vs-legacy equality on a real explorer run
# ---------------------------------------------------------------------------
def test_explorer_publishes_registry_equal_to_legacy_stats():
    from mythril_tpu.laser.batch.explore import (
        MERGE_POLICY,
        DeviceCorpusExplorer,
    )

    marker = registry().marker()
    explorer = DeviceCorpusExplorer(
        [TINY], lanes_per_contract=8, waves=2, steps_per_wave=64,
        budget_s=30,
    )
    stats = explorer.run()["stats"]
    delta = registry().since(marker)
    assert stats["waves"] >= 1 and stats["device_steps"] > 0
    for field, policy in MERGE_POLICY.items():
        value = stats.get(field)
        if not isinstance(value, (int, float)):
            continue
        if policy == "sum":
            got = delta.get(f"mtpu_explore_{field}_total", {}).get((), 0)
            assert got == pytest.approx(value), (field, got, value)
        elif policy == "max":
            got = registry().value(f"mtpu_explore_{field}_max")
            assert got >= value, (field, got, value)
    # the run left its span trail
    names = {s.name for s in flight_recorder().tail(4096)}
    assert {"explore.run", "wave.dispatch", "wave.device"} <= names


# ---------------------------------------------------------------------------
# service: atomic /stats + /metrics + /trace + drain flush
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def live_server():
    from mythril_tpu.service.engine import ServiceConfig
    from mythril_tpu.service.server import AnalysisServer

    config = ServiceConfig(
        stripes=2, lanes_per_stripe=4, steps_per_wave=64, max_waves=1,
        host_walk=False, coalesce_wait_s=0.01,
    )
    server = AnalysisServer(config).start()
    yield server
    server.close()


def _get(url: str):
    import urllib.request

    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.headers.get("Content-Type", ""), resp.read()


def test_service_stats_metrics_trace_endpoints(live_server):
    from mythril_tpu.service.client import ServiceClient
    from mythril_tpu.service.engine import STATS_SCHEMA_VERSION

    client = ServiceClient(live_server.url)
    job_id = client.submit(TINY)
    report = client.report(job_id, wait_s=180.0)
    assert report["state"] == "done", report

    stats = client.stats()
    assert stats["schema_version"] == STATS_SCHEMA_VERSION
    assert stats["waves"]["count"] >= 1
    assert stats["observe"]["enabled"] is True

    ctype, body = _get(live_server.url + "/metrics")
    assert ctype.startswith("text/plain")
    text = body.decode()
    assert "# TYPE mtpu_service_waves_total counter" in text
    assert "mtpu_service_admissions_total" in text
    # the engine's series carry its instance label
    eid = live_server.engine._eid
    assert f'mtpu_service_waves_total{{engine="{eid}"}}' in text

    _ctype, body = _get(live_server.url + "/trace?n=64")
    doc = json.loads(body)
    assert doc["schema_version"] == SCHEMA_VERSION
    names = {s["name"] for s in doc["spans"]}
    assert "service.wave.dispatch" in names

    _ctype, body = _get(live_server.url + "/trace?format=perfetto")
    assert json.loads(body)["traceEvents"]


def test_service_drain_flushes_flight_recorder(tmp_path):
    from mythril_tpu.service.engine import AnalysisEngine, ServiceConfig

    engine = AnalysisEngine(
        ServiceConfig(
            stripes=2, lanes_per_stripe=4, checkpoint_dir=str(tmp_path)
        )
    )
    engine.drain()
    dump = engine.flight_dump_path
    assert dump and os.path.exists(dump)
    assert json.loads(open(dump).read()).get("traceEvents") is not None
    assert engine.stats()["observe"]["flight_dump"] == dump
