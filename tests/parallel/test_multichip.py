"""The multichip suite (tier-1 port of the driver's dryrun_multichip):
every multi-device behavior pinned as pytest on the 8 simulated host
devices tests/conftest.py forces (--xla_force_host_platform_device_count=8).

Covers the dryrun sections — sharded step, symbolic shadow step,
solver portfolio and batched solve over the mesh — plus the multi-chip
corpus scheduler (parallel/scheduler.py): the N-device-vs-1-device
corpus-to-issues differential on the fault-suite contracts, the
work-steal path (a drained shard demonstrably takes load from a loaded
one), the frontier handoff, and the per-group failure domain (a
faulted group degrades only its own shard)."""

import numpy as np
import pytest

import jax

from mythril_tpu.parallel import discover_topology
from mythril_tpu.parallel.scheduler import CorpusScheduler
from mythril_tpu.support import resilience

pytestmark = pytest.mark.multichip

#: the fault-suite contracts (tests/laser/test_pipeline.py)
KILLABLE = "33ff"
WRITER = "6001600055600060015500"
BRANCHER = "600035600757005b600160005500"
GATED = "60003560f81c604214600d57005b600160005500"
FAULT_SUITE = [KILLABLE, WRITER, BRANCHER, GATED]

#: lean explorer shape shared by the scheduler tests (fast on CPU)
EXPLORE_KW = dict(
    lanes_per_contract=8, waves=3, steps_per_wave=64, transaction_count=1
)


@pytest.fixture(autouse=True)
def _clean_supervisor():
    resilience.disarm_faults()
    resilience.DegradationLog().reset()
    yield
    resilience.disarm_faults()


def test_eight_simulated_devices_present():
    assert len(jax.devices()) >= 8


# -- the dryrun sections, as pytest -----------------------------------------
def test_step_shards_over_the_mesh():
    """dryrun section 1: the batched concrete step jit'd over an
    8-device dp mesh."""
    from __graft_entry__ import _demo_workload
    from mythril_tpu.laser.batch.step import step
    from mythril_tpu.parallel import (
        batch_sharding,
        make_mesh,
        replicate_table,
        replicated,
        shard_batch,
    )

    mesh = make_mesh(8)
    batch, code = _demo_workload(n_lanes=64)
    batch = shard_batch(batch, mesh)
    code = replicate_table(code, mesh)
    sharded_step = jax.jit(
        step,
        in_shardings=(
            jax.tree.map(lambda _: batch_sharding(mesh), batch),
            jax.tree.map(lambda _: replicated(mesh), code),
        ),
        out_shardings=jax.tree.map(lambda _: batch_sharding(mesh), batch),
    )
    out = sharded_step(batch, code)
    jax.block_until_ready(out)
    assert out.pc.shape == batch.pc.shape


def test_symbolic_shadow_step_shards_over_the_mesh():
    """dryrun section 2: lane-major shadow state shards with the
    lanes; the shared expression arena replicates."""
    from __graft_entry__ import _demo_workload
    from mythril_tpu.laser.batch.symbolic import make_sym_batch, sym_step
    from mythril_tpu.parallel import (
        batch_sharding,
        make_mesh,
        replicate_table,
        replicated,
        shard_batch,
    )

    mesh = make_mesh(8)
    batch, code = _demo_workload(n_lanes=64)
    batch = shard_batch(batch, mesh)
    code = replicate_table(code, mesh)
    symb = make_sym_batch(batch)
    lane_sharded = {"stack_tid", "mem_tid", "skey_tid", "sval_tid", "br_tid"}
    symb = symb._replace(
        base=batch,
        **{
            name: jax.device_put(
                getattr(symb, name),
                batch_sharding(mesh)
                if name in lane_sharded
                else replicated(mesh),
            )
            for name in (
                "stack_tid", "mem_tid", "skey_tid", "sval_tid", "br_tid",
                "ar_op", "ar_a", "ar_b", "ar_va", "ar_vb", "ar_count",
            )
        },
    )
    out = jax.jit(sym_step)(symb, code)
    jax.block_until_ready(out)
    assert out.stack_tid.shape == symb.stack_tid.shape


def test_solver_portfolio_replicates_over_devices():
    """dryrun sections 3+4: per-device solver replicas and the batched
    query solve sharded over the mesh."""
    from mythril_tpu.laser.smt import symbol_factory
    from mythril_tpu.laser.smt.evalterm import eval_term
    from mythril_tpu.laser.smt.solver.portfolio import (
        device_check,
        device_check_batch,
    )
    from mythril_tpu.laser.smt.solver.solver import lower

    x = symbol_factory.BitVecSym("mc_x", 64)
    cons, _ = lower([(x + 5 == 12).raw])
    asn = device_check(cons, candidates=32, steps=2048, n_devices=8)
    assert asn is not None and all(eval_term(c, asn) for c in cons)

    ys = [symbol_factory.BitVecSym(f"mc_y{i}", 32) for i in range(4)]
    queries = [
        lower([(y * 3 == 21 + 3 * i).raw])[0] for i, y in enumerate(ys)
    ]
    found = device_check_batch(
        queries, candidates=32, steps=1024, n_devices=8
    )
    solved = 0
    for q, a in zip(queries, found):
        if a is not None:
            assert all(eval_term(c, a) for c in q)
            solved += 1
    assert solved >= 1, "batched mesh solve found nothing"


# -- topology ----------------------------------------------------------------
def test_topology_splits_devices_into_groups():
    topo = discover_topology(4)
    assert topo.n_groups == 4
    assert topo.n_devices == len(jax.devices())
    sizes = [len(g.devices) for g in topo.groups]
    assert max(sizes) - min(sizes) <= 1
    flat = [d for g in topo.groups for d in g.devices]
    assert len(set(map(str, flat))) == len(flat)  # no device in two groups


def test_topology_clamps_to_device_count():
    topo = discover_topology(100)
    assert topo.n_groups == len(jax.devices())
    assert all(len(g.devices) == 1 for g in topo.groups)


def test_group_shrinks_device_set_to_divide_lanes():
    group = discover_topology(2).group(0)
    assert len(group.devices_for_lanes(len(group.devices) * 8)) == len(
        group.devices
    )
    assert len(group.devices_for_lanes(7)) == 1


# -- the corpus-to-issues differential (acceptance criterion) ----------------
def _issue_set(contracts_outcomes):
    """The issue-bearing fingerprint of a scheduler run: synthesized
    Issues from the evidence bank plus the trigger classes/pcs — the
    exact inputs issue synthesis (analysis/evidence.py + prepass
    witnesses) consumes."""
    from mythril_tpu.analysis.evidence import evidence_issues

    class _C:
        def __init__(self, code):
            self.code = code
            self.name = "t"
            self.creation_code = None

    out = []
    for code, outcome in zip(FAULT_SUITE, contracts_outcomes):
        issues = {
            (i.swc_id, i.address)
            for i in evidence_issues(_C(code), outcome, 0x1234)
        }
        triggers = {
            kind: tuple(sorted(t["pc"] for t in bucket))
            for kind, bucket in (outcome.get("triggers") or {}).items()
        }
        out.append((issues, triggers))
    return out


def test_n_device_issue_set_matches_single_device():
    """The differential: the corpus explored over 2 device groups must
    produce the same issue set as the 1-group run on the fault-suite
    contracts (and the same gated-branch coverage)."""
    one = CorpusScheduler(
        FAULT_SUITE, n_groups=1, chunk=len(FAULT_SUITE), parallel=False,
        shard="round-robin", explorer_kwargs=dict(EXPLORE_KW),
    ).run()
    two = CorpusScheduler(
        FAULT_SUITE, n_groups=2, chunk=1, parallel=False,
        shard="round-robin", explorer_kwargs=dict(EXPLORE_KW),
    ).run()
    assert _issue_set(one["contracts"]) == _issue_set(two["contracts"])
    # the differential is not trivially empty: the selfdestruct fires
    # and the gated branch needed a solver flip on BOTH runs
    for result in (one, two):
        assert "selfdestruct" in result["contracts"][0]["triggers"]
        covered = {
            tuple(b) for b in result["contracts"][3]["covered_branches"]
        }
        assert (11, True) in covered and (11, False) in covered
    assert two["stats"]["mesh_groups"] == 2
    assert two["stats"]["mesh_devices"] == len(jax.devices())


def test_outcomes_annotated_with_their_group():
    out = CorpusScheduler(
        FAULT_SUITE, n_groups=2, chunk=1, parallel=False,
        shard="round-robin", explorer_kwargs=dict(EXPLORE_KW),
    ).run()
    groups = [c["mesh_group"] for c in out["contracts"]]
    assert set(groups) == {0, 1}  # both shards carried contracts


# -- work stealing (acceptance criterion) ------------------------------------
def test_drained_shard_steals_from_loaded_shard():
    """Group 1 is admitted one contract while group 0 holds three:
    after its own queue drains, group 1 must take load from group 0
    (steal counter > 0), and the stolen contract's outcome must come
    from the thief."""
    sched = CorpusScheduler(
        [BRANCHER, WRITER, GATED, KILLABLE],
        n_groups=2,
        chunk=1,
        parallel=False,
        shard=[0, 0, 0, 1],  # the imbalance: 3 vs 1
        explorer_kwargs=dict(EXPLORE_KW),
    )
    out = sched.run()
    stats = out["stats"]
    assert stats["steal_count"] > 0
    assert stats["stolen_items"] > 0
    assert stats["rebalance_bytes"] > 0
    per = {g["group"]: g for g in stats["mesh"]["per_device"]}
    assert per[1]["steals"] > 0  # the drained shard initiated it
    assert per[0]["victim_items"] > 0  # ...from the loaded one
    # the stolen contract (GATED, admitted to group 0) ran on group 1
    assert out["contracts"][2]["mesh_group"] == 1
    # and its exploration is not degraded by the move: the gated
    # branch still flips on the thief's device
    covered = {tuple(b) for b in out["contracts"][2]["covered_branches"]}
    assert (11, True) in covered and (11, False) in covered


# slow tier: ~40 s of threaded 8-contract exploration; tier-1 keeps
# the deterministic sequential schedule + steal + fault pins
@pytest.mark.slow
def test_threaded_schedule_completes_all_contracts():
    """The production (threaded) schedule: every contract gets an
    outcome, and both groups did work."""
    out = CorpusScheduler(
        FAULT_SUITE * 2,
        n_groups=2,
        chunk=2,
        parallel=True,
        explorer_kwargs=dict(EXPLORE_KW),
    ).run()
    assert len(out["contracts"]) == 8
    assert all(
        "covered_branches" in c for c in out["contracts"]
    ), "a contract lost its outcome"
    per = {g["group"]: g for g in out["stats"]["mesh"]["per_device"]}
    assert per[0]["waves"] > 0 and per[1]["waves"] > 0


# -- frontier handoff --------------------------------------------------------
def test_frontier_handoff_roundtrip():
    """export_frontier -> seed_frontier continues the donor's
    exploration: the continuation starts with the donor's coverage and
    blacklists, and its outcome keeps every donor-covered branch."""
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

    donor = DeviceCorpusExplorer([GATED], **EXPLORE_KW)
    donor_out = donor.run()
    frontier = donor.export_frontier(0)
    assert frontier["parent_inputs"], "donor exported no seeds"
    donor_covered = {
        tuple(b) for b in donor_out["contracts"][0]["covered_branches"]
    }

    thief = DeviceCorpusExplorer([GATED], **EXPLORE_KW)
    thief.seed_frontier(0, frontier)
    # the donor's solved flips stay blacklisted on the thief
    assert thief.tracks[0].attempted
    cont = thief.run()["contracts"][0]
    assert donor_covered <= {tuple(b) for b in cont["covered_branches"]}


def test_frontier_handoff_refuses_wrong_contract():
    from mythril_tpu.laser.batch.explore import DeviceCorpusExplorer

    donor = DeviceCorpusExplorer([GATED], **EXPLORE_KW)
    donor.run()
    frontier = donor.export_frontier(0)
    thief = DeviceCorpusExplorer([WRITER], **EXPLORE_KW)
    with pytest.raises(ValueError):
        thief.seed_frontier(0, frontier)


# -- failure domains (acceptance criterion) ----------------------------------
def test_faulted_group_degrades_only_its_own_shard():
    """A device fault injected into group 0's dispatches (the
    domain-qualified site device.dispatch.mesh-g0, times=99 so the
    whole retry ladder is exhausted) demotes ONLY group 0's shard:
    its contracts lose device-completeness, group 1's results are
    identical to a fault-free run, and the DegradationLog attributes
    the group."""
    clean = CorpusScheduler(
        FAULT_SUITE, n_groups=2, chunk=2, parallel=False,
        shard="round-robin", explorer_kwargs=dict(EXPLORE_KW),
    ).run()

    resilience.DegradationLog().reset()
    resilience.arm_fault("device.dispatch.mesh-g0", times=99)
    try:
        faulted = CorpusScheduler(
            FAULT_SUITE, n_groups=2, chunk=2, parallel=False,
            shard="round-robin", explorer_kwargs=dict(EXPLORE_KW),
        ).run()
    finally:
        resilience.disarm_faults()

    # group 0's shard (round-robin: contracts 0 and 2) degraded
    for i in (0, 2):
        assert faulted["contracts"][i]["mesh_group"] == 0
        assert not faulted["contracts"][i]["device_complete"]
    # group 1's shard is untouched: same fingerprint as the clean run
    for i in (1, 3):
        assert faulted["contracts"][i]["mesh_group"] == 1
        assert faulted["contracts"][i]["device_complete"] == (
            clean["contracts"][i]["device_complete"]
        )
        assert (
            faulted["contracts"][i]["covered_branches"]
            == clean["contracts"][i]["covered_branches"]
        )
        assert (
            faulted["contracts"][i]["triggers"].keys()
            == clean["contracts"][i]["triggers"].keys()
        )
    # the DegradationLog attributes the group
    log = resilience.DegradationLog()
    assert log.counts.get("mesh-group-degraded", 0) >= 1
    sites = {
        e["site"]
        for e in log.events
        if e["reason"] == "mesh-group-degraded"
    }
    assert sites == {"mesh-g0"}
    per = {
        g["group"]: g
        for g in faulted["stats"]["mesh"]["per_device"]
    }
    assert per[0]["faults"] >= 1 and per[0]["degraded_contracts"] >= 1
    assert per[1]["faults"] == 0


# -- the prepass integration -------------------------------------------------
def test_corpus_prepass_routes_through_the_scheduler():
    """corpus_device_prepass(mesh_groups=2) must run the scheduler
    (mesh counters present) and keep the outcome contract the
    per-contract consumers read."""
    from mythril_tpu.analysis.corpus import corpus_device_prepass

    # the dryrun's gated-selfdestruct contract replaces bare KILLABLE:
    # _runnable_rows drops codes under 4 bytes from any prepass
    gated_kill = "604260003560f81c14600d57005b33ff"
    rows = [
        (code, "", f"c{i}")
        for i, code in enumerate([gated_kill, WRITER, BRANCHER, GATED])
    ]
    out = corpus_device_prepass(
        rows, budget_s=60.0, transaction_count=1, mesh_groups=2
    )
    assert set(out) == {0, 1, 2, 3}
    stats = out[0]["stats"]
    assert stats["mesh_groups"] == 2
    assert stats["scope"] == "corpus"
    assert "steal_count" in stats and "rebalance_bytes" in stats
    assert len(stats["mesh"]["per_device"]) == 2
    # the gated SELFDESTRUCT needs a solver flip — the mesh run banks
    # its trigger end-to-end, the same bar the dryrun asserted
    assert "selfdestruct" in out[0]["triggers"]
